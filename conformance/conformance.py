"""Platform conformance suite (ref conformance/1.5: a runnable program
that certifies a deployment exposes the required capabilities).

The reference's program deploys in-cluster test runners (`Makefile:16-30`,
KFP-only targets); ours certifies the capability list of SURVEY.md §2
against a live Cluster: CRDs registered, notebook lifecycle, TPU env
injection, gang atomicity, tenancy isolation, culling knobs, web surface.
Run: `python conformance/conformance.py` — exits non-zero on failure,
prints a JSON report.
"""

from __future__ import annotations

import json
import os
import sys

# Runnable as `python conformance/conformance.py` or `python
# loadtest/loadtest.py` without installing the package: script
# execution puts the SCRIPT's dir on sys.path, not the repo root.
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import traceback
from typing import Callable

from kubeflow_tpu.api.core import Container, PodTemplateSpec, registered_kinds
from kubeflow_tpu.api.crds import Notebook, Profile, TpuPodDefault
from kubeflow_tpu.controlplane.cluster import Cluster, ClusterConfig
from kubeflow_tpu.controlplane import webhook as wh

CHECKS: list[tuple[str, Callable[[Cluster], None]]] = []


def check(name: str):
    def deco(fn):
        CHECKS.append((name, fn))
        return fn
    return deco


def _nb(name: str, ns: str = "conf", topology: str = "") -> Notebook:
    nb = Notebook()
    nb.metadata.name = name
    nb.metadata.namespace = ns
    nb.spec.template = PodTemplateSpec()
    nb.spec.template.spec.containers.append(
        Container(name=name, image="kubeflow-tpu/jupyter-jax:latest"))
    nb.spec.tpu.topology = topology
    return nb


@check("crds-registered")
def crds_registered(c: Cluster) -> None:
    kinds = registered_kinds()
    for k in ("Notebook", "Profile", "TpuPodDefault", "Tensorboard",
              "Experiment", "Trial", "ModelServer"):
        assert k in kinds, f"CRD {k} not registered"


@check("notebook-lifecycle")
def notebook_lifecycle(c: Cluster) -> None:
    c.store.create(_nb("life"))
    assert c.wait_idle()
    sts = c.store.get("StatefulSet", "conf", "life")
    assert sts.ready_replicas == 1
    c.store.delete("Notebook", "conf", "life")
    assert c.wait_idle()
    assert c.store.try_get("StatefulSet", "conf", "life") is None


@check("tpu-env-injection")
def tpu_env_injection(c: Cluster) -> None:
    c.store.create(_nb("gang", topology="v5e-16"))
    assert c.wait_idle()
    pods = c.store.list("Pod", "conf",
                        label_selector={"notebook-name": "gang"})
    assert len(pods) == 4, f"want 4 gang hosts, got {len(pods)}"
    for p in pods:
        env = {e.name: e.value for e in p.spec.containers[0].env}
        assert "TPU_WORKER_ID" in env and "TPU_WORKER_HOSTNAMES" in env
        assert env.get("JAX_COORDINATOR_ADDRESS"), "coordinator missing"


@check("gang-atomicity")
def gang_atomicity(c: Cluster) -> None:
    c.store.create(_nb("gang2", topology="v5e-16"))  # pool has 1 slice
    assert c.wait_idle()
    for sts_name in ("gang", "gang2"):
        sts = c.store.try_get("StatefulSet", "conf", sts_name)
        if sts is not None:
            assert sts.ready_replicas in (0, sts.spec.replicas), (
                f"partial gang: {sts_name} {sts.ready_replicas}")


@check("poddefault-injection")
def poddefault_injection(c: Cluster) -> None:
    pd = TpuPodDefault()
    pd.metadata.name = "conf-pd"
    pd.metadata.namespace = "conf"
    pd.spec.selector = {"notebook-name": "withpd"}
    from kubeflow_tpu.api.core import EnvVar
    pd.spec.env = [EnvVar("CONF_CHECK", "yes")]
    c.store.create(pd)
    c.store.create(_nb("withpd"))
    assert c.wait_idle()
    pod = c.store.get("Pod", "conf", "withpd-0")
    env = {e.name: e.value for e in pod.spec.containers[0].env}
    assert env.get("CONF_CHECK") == "yes"


@check("tenancy-profile")
def tenancy_profile(c: Cluster) -> None:
    p = Profile()
    p.metadata.name = "conf-user"
    p.spec.owner = "conf@example.com"
    c.store.create(p)
    assert c.wait_idle()
    assert c.store.get("Namespace", "", "conf-user")
    assert c.store.get("ServiceAccount", "conf-user", "default-editor")
    assert c.store.get("RoleBinding", "conf-user", "default-editor")


@check("multiversion-conversion")
def multiversion_conversion(c: Cluster) -> None:
    """Old-client compatibility: Notebook AND Profile serve every
    registered version with lossless round-trips (ref conversion files
    beside notebook_types.go / profile_types.go)."""
    from kubeflow_tpu.api import versioning

    for kind, versions in versioning.SERVED_VERSIONS.items():
        assert versioning.STORAGE_VERSION in versions, (kind, versions)
        assert len(versions) >= 2, f"{kind} serves a single version"
    # Profile: wire round-trip through the down-level version
    wire = {"apiVersion": f"{versioning.GROUP}/v1beta1", "kind": "Profile",
            "metadata": {"name": "conf-mv"},
            "spec": {"owner": {"kind": "User", "name": "mv@example.com"},
                     "resourceQuotaSpec": {"hard": {"tpu/v5e-chips": "8"}}}}
    hub = versioning.convert_dict(dict(wire), versioning.STORAGE_VERSION)
    back = versioning.convert_dict(hub, "v1beta1")
    assert back["spec"]["owner"]["name"] == "mv@example.com"
    assert back["spec"]["resourceQuotaSpec"]["hard"] == {
        "tpu/v5e-chips": "8"}


@check("spawner-placement-groups")
def spawner_placement_groups(c: Cluster) -> None:
    """Admin placement groups land on the gang pod template (ref
    form.py:178-223)."""
    from kubeflow_tpu.web import form as form_lib

    f = form_lib.parse_form({
        "name": "conf-placed", "namespace": "conf",
        "tpu": {"topology": "", "mesh": ""},
        "affinityConfig": "tpu-v5e-pool",
        "tolerationGroup": "tpu-reserved"})
    nb = form_lib.build_notebook(f)
    assert any(t.key == "cloud.google.com/gke-tpu-accelerator"
               for t in nb.spec.template.spec.affinity_terms)
    assert any(t.key == "google.com/tpu"
               for t in nb.spec.template.spec.tolerations)


@check("modelserver-lifecycle")
def modelserver_lifecycle(c: Cluster) -> None:
    """Serving deploys through the platform: CR → Deployment running
    the serving CLI behind the /serving route, readiness mirrored."""
    from kubeflow_tpu.api.crds import ModelServer

    ms = ModelServer()
    ms.metadata.name = "conf-srv"
    ms.metadata.namespace = "conf"
    ms.spec.model = "llama-tiny"
    c.store.create(ms)
    assert c.wait_idle()
    dep = c.store.get("Deployment", "conf", "conf-srv")
    assert dep.spec.template.spec.containers[0].command == [
        "python", "-m", "kubeflow_tpu.serving"]
    got = c.store.get("ModelServer", "conf", "conf-srv")
    assert got.status.ready and got.status.url == "/serving/conf/conf-srv/"
    c.store.delete("ModelServer", "conf", "conf-srv")
    assert c.wait_idle()
    assert c.store.try_get("Deployment", "conf", "conf-srv") is None


def main() -> int:
    cfg = ClusterConfig(tpu_slices={"v5e-16": 1})
    results = []
    ok = True
    with Cluster(cfg) as c:
        for name, fn in CHECKS:
            try:
                fn(c)
                results.append({"check": name, "status": "PASS"})
            except Exception as e:  # noqa: BLE001 — report and continue
                ok = False
                results.append({"check": name, "status": "FAIL",
                                "error": f"{e}",
                                "trace": traceback.format_exc(limit=3)})
    print(json.dumps({"conformance": results,
                      "passed": sum(r["status"] == "PASS" for r in results),
                      "total": len(results)}, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
