"""kubeflow-tpu: a TPU-native ML platform.

A brand-new framework with the capabilities of the Kubeflow components repo
(reference: ODH fork of kubeflow/kubeflow), re-designed TPU-first:

- ``kubeflow_tpu.api`` / ``kubeflow_tpu.controlplane``: the control plane —
  typed resources (Notebook, Profile, TpuPodDefault, Tensorboard), an
  object store with watches, a reconciler runtime, controllers, and the
  TPU env-injection webhook (the NCCL-free multi-host bootstrap).
- ``kubeflow_tpu.parallel``: device meshes, sharding rules, FSDP/TP/SP/EP
  parallelism built on jax.sharding + shard_map.
- ``kubeflow_tpu.models`` / ``kubeflow_tpu.ops``: model families (Llama,
  ViT, Gemma, MLP) and TPU kernels (Pallas flash attention, ring attention).
- ``kubeflow_tpu.train``: training loop, optimizer, checkpointing.
- ``kubeflow_tpu.serving``: jax2tf/SavedModel and pure-JAX serving.
- ``kubeflow_tpu.distributed``: multi-host bootstrap from injected env.
"""

__version__ = "0.1.0"
