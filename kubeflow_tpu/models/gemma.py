"""Gemma family, TPU-first (BASELINE config "Gemma-2B jax2tf serving").

Same stacked-layers/`lax.scan` + logical-axes design as models/llama.py;
the Gemma-specific differences are kept explicit:
  - tied embeddings ALWAYS, with sqrt(hidden) embedding scaling;
  - GeGLU MLP (gelu gate, not silu);
  - multi-query attention (num_kv_heads=1 for 2B), head_dim 256;
  - rope theta 10000, norm eps 1e-6.

Reference parity: the reference serves models via the (removed)
TF-Serving path (`/root/reference/docs_dev/tf_serving.md:1-60`); this is
the model that kubeflow_tpu.serving exports the TPU-native way.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import apply_rope, rope_frequencies
from kubeflow_tpu.parallel.sharding import with_sharding_constraint as wsc

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GemmaConfig:
    vocab_size: int = 256128
    hidden_size: int = 2048
    intermediate_size: int = 16384
    num_layers: int = 18
    num_heads: int = 8
    num_kv_heads: int = 1
    head_dim: int = 256
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # Sliding-window attention (Gemma-2 uses 4096 on alternating
    # layers; here it applies model-wide like llama.LlamaConfig).
    sliding_window: int | None = None

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim


GEMMA_2B = GemmaConfig()
GEMMA_TINY = GemmaConfig(
    vocab_size=512, hidden_size=128, intermediate_size=512, num_layers=2,
    num_heads=4, num_kv_heads=1, head_dim=32, dtype=jnp.float32, remat=False,
)

CONFIGS = {"gemma-2b": GEMMA_2B, "tiny": GEMMA_TINY}


def param_logical_axes(cfg: GemmaConfig) -> Params:
    return {
        "embed": ("vocab", "embed"),
        "blocks": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": ("embed",),
    }


def init(rng: jax.Array, cfg: GemmaConfig) -> Params:
    keys = iter(jax.random.split(rng, 16))
    pd = cfg.param_dtype

    def dense(key, shape, fan_in):
        return (jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(pd)

    L, D = cfg.num_layers, cfg.hidden_size
    return {
        "embed": dense(next(keys), (cfg.vocab_size, D), D),
        "blocks": {
            "attn_norm": jnp.zeros((L, D), pd),
            "wq": dense(next(keys), (L, D, cfg.q_dim), D),
            "wk": dense(next(keys), (L, D, cfg.kv_dim), D),
            "wv": dense(next(keys), (L, D, cfg.kv_dim), D),
            "wo": dense(next(keys), (L, cfg.q_dim, D), cfg.q_dim),
            "mlp_norm": jnp.zeros((L, D), pd),
            "w_gate": dense(next(keys), (L, D, cfg.intermediate_size), D),
            "w_up": dense(next(keys), (L, D, cfg.intermediate_size), D),
            "w_down": dense(next(keys), (L, cfg.intermediate_size, D),
                            cfg.intermediate_size),
        },
        "final_norm": jnp.zeros((D,), pd),
    }


def _block(cfg: GemmaConfig, x, p, positions, inv_freq, kv_mask,
           contiguous_positions=False):
    b, s, D = x.shape
    h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
    q = (h @ p["wq"].astype(cfg.dtype)).reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = (h @ p["wk"].astype(cfg.dtype)).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    v = (h @ p["wv"].astype(cfg.dtype)).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    q = wsc(q, ("batch", "seq", "act_heads", None))
    attn = dot_product_attention(q, k, v, positions, positions,
                                 causal=True, kv_mask=kv_mask,
                                 window=cfg.sliding_window,
                                 contiguous_positions=contiguous_positions)
    x = x + attn.reshape(b, s, cfg.q_dim) @ p["wo"].astype(cfg.dtype)
    x = wsc(x, ("batch", "seq", "act_embed"))

    h = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    # GeGLU: gelu(gate) * up — the Gemma MLP.
    gate = jax.nn.gelu(h @ p["w_gate"].astype(cfg.dtype), approximate=True)
    up = h @ p["w_up"].astype(cfg.dtype)
    ff = wsc(gate * up, ("batch", "seq", "act_mlp"))
    x = x + ff @ p["w_down"].astype(cfg.dtype)
    return wsc(x, ("batch", "seq", "act_embed"))


def apply(
    params: Params,
    cfg: GemmaConfig,
    tokens: jnp.ndarray,                 # [b, s] int32
    positions: jnp.ndarray | None = None,
    kv_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Forward → logits [b, s, vocab] fp32. Tied head (embed.T)."""
    b, s = tokens.shape
    contiguous = positions is None
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    inv_freq = rope_frequencies(cfg.head_dim, theta=cfg.rope_theta)

    from kubeflow_tpu.models.llama import _embed_lookup

    x = _embed_lookup(params["embed"], tokens, cfg.dtype)
    x = x * jnp.asarray(cfg.hidden_size ** 0.5, cfg.dtype)  # Gemma scaling
    x = wsc(x, ("batch", "seq", "act_embed"))

    block_fn = lambda x, lp: (
        _block(cfg, x, lp, positions, inv_freq, kv_mask,
               contiguous_positions=contiguous), None)
    if cfg.remat:
        block_fn = jax.checkpoint(block_fn)
    x, _ = jax.lax.scan(block_fn, x, params["blocks"])

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ params["embed"].T.astype(jnp.float32)
    return wsc(logits, ("batch", "seq", "act_vocab"))
