"""Vision Transformer, TPU-first (BASELINE config "ViT-B/16 fine-tune").

Patchify is a single reshape + dense (a 16x16-stride conv is exactly a
[P*P*C, D] matmul on non-overlapping patches — one big MXU-friendly GEMM
instead of a conv XLA must re-window). Blocks are stacked on a leading
layers axis and scanned, like models/llama.py. Pre-LN, learned position
embeddings, mean-pool head (configurable CLS token).

Logical axes reuse the LLAMA_RULES vocabulary ("embed"→fsdp,
"heads"/"mlp"→tensor, classifier "vocab"→tensor), so the same
ShardingRules drive FSDP/TP fine-tuning with zero model changes.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.ops.norms import layer_norm
from kubeflow_tpu.parallel.sharding import with_sharding_constraint as wsc

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    num_classes: int = 1000
    norm_eps: float = 1e-6
    use_cls_token: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.num_heads == 0
        return self.hidden_size // self.num_heads

    @property
    def num_patches(self) -> int:
        assert self.image_size % self.patch_size == 0
        return (self.image_size // self.patch_size) ** 2

    @property
    def seq_len(self) -> int:
        return self.num_patches + (1 if self.use_cls_token else 0)

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.num_channels


VIT_B16 = ViTConfig()
VIT_TINY = ViTConfig(
    image_size=32, patch_size=8, hidden_size=64, intermediate_size=128,
    num_layers=2, num_heads=4, num_classes=10, dtype=jnp.float32, remat=False,
)

CONFIGS = {"vit-b16": VIT_B16, "tiny": VIT_TINY}


def param_logical_axes(cfg: ViTConfig) -> Params:
    axes: Params = {
        "patch_embed": (None, "embed"),
        "patch_bias": ("embed",),
        "pos_embed": (None, "embed"),
        "blocks": {
            "ln1_w": ("layers", "embed"), "ln1_b": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "heads"),
            "wv": ("layers", "embed", "heads"),
            "bq": ("layers", "heads"), "bk": ("layers", "heads"),
            "bv": ("layers", "heads"),
            "wo": ("layers", "heads", "embed"), "bo": ("layers", "embed"),
            "ln2_w": ("layers", "embed"), "ln2_b": ("layers", "embed"),
            "w1": ("layers", "embed", "mlp"), "b1": ("layers", "mlp"),
            "w2": ("layers", "mlp", "embed"), "b2": ("layers", "embed"),
        },
        "final_ln_w": ("embed",), "final_ln_b": ("embed",),
        "head_w": ("embed", "vocab"), "head_b": ("vocab",),
    }
    if cfg.use_cls_token:
        axes["cls_token"] = (None, "embed")
    return axes


def init(rng: jax.Array, cfg: ViTConfig) -> Params:
    keys = iter(jax.random.split(rng, 24))
    pd = cfg.param_dtype

    def dense(key, shape, fan_in):
        return (jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(pd)

    L, D, M = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    params: Params = {
        "patch_embed": dense(next(keys), (cfg.patch_dim, D), cfg.patch_dim),
        "patch_bias": jnp.zeros((D,), pd),
        "pos_embed": (jax.random.normal(next(keys), (cfg.seq_len, D))
                      * 0.02).astype(pd),
        "blocks": {
            "ln1_w": jnp.ones((L, D), pd), "ln1_b": jnp.zeros((L, D), pd),
            "wq": dense(next(keys), (L, D, D), D),
            "wk": dense(next(keys), (L, D, D), D),
            "wv": dense(next(keys), (L, D, D), D),
            "bq": jnp.zeros((L, D), pd), "bk": jnp.zeros((L, D), pd),
            "bv": jnp.zeros((L, D), pd),
            "wo": dense(next(keys), (L, D, D), D),
            "bo": jnp.zeros((L, D), pd),
            "ln2_w": jnp.ones((L, D), pd), "ln2_b": jnp.zeros((L, D), pd),
            "w1": dense(next(keys), (L, D, M), D),
            "b1": jnp.zeros((L, M), pd),
            "w2": dense(next(keys), (L, M, D), M),
            "b2": jnp.zeros((L, D), pd),
        },
        "final_ln_w": jnp.ones((D,), pd),
        "final_ln_b": jnp.zeros((D,), pd),
        # Zero-init head: standard fine-tune recipe (fresh classes).
        "head_w": jnp.zeros((D, cfg.num_classes), pd),
        "head_b": jnp.zeros((cfg.num_classes,), pd),
    }
    if cfg.use_cls_token:
        params["cls_token"] = (jax.random.normal(next(keys), (1, D))
                               * 0.02).astype(pd)
    return params


def patchify(cfg: ViTConfig, images: jnp.ndarray) -> jnp.ndarray:
    """[b, H, W, C] → [b, n_patches, P*P*C] by pure reshape/transpose."""
    b, H, W, C = images.shape
    P = cfg.patch_size
    gh, gw = H // P, W // P
    x = images.reshape(b, gh, P, gw, P, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)            # [b, gh, gw, P, P, C]
    return x.reshape(b, gh * gw, P * P * C)


def _block(cfg: ViTConfig, x, p):
    b, s, D = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    dt = cfg.dtype
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))

    h = layer_norm(x, p["ln1_w"], p["ln1_b"], cfg.norm_eps)
    q = (h @ p["wq"].astype(dt) + p["bq"].astype(dt)).reshape(b, s, nh, hd)
    k = (h @ p["wk"].astype(dt) + p["bk"].astype(dt)).reshape(b, s, nh, hd)
    v = (h @ p["wv"].astype(dt) + p["bv"].astype(dt)).reshape(b, s, nh, hd)
    q = wsc(q, ("batch", "seq", "act_heads", None))
    attn = dot_product_attention(q, k, v, pos, pos, causal=False)
    attn = attn.reshape(b, s, D)
    x = x + attn @ p["wo"].astype(dt) + p["bo"].astype(dt)
    x = wsc(x, ("batch", "seq", "act_embed"))

    h = layer_norm(x, p["ln2_w"], p["ln2_b"], cfg.norm_eps)
    h = jax.nn.gelu(h @ p["w1"].astype(dt) + p["b1"].astype(dt))
    h = wsc(h, ("batch", "seq", "act_mlp"))
    x = x + h @ p["w2"].astype(dt) + p["b2"].astype(dt)
    return wsc(x, ("batch", "seq", "act_embed"))


def apply(params: Params, cfg: ViTConfig, images: jnp.ndarray) -> jnp.ndarray:
    """[b, H, W, C] float images → logits [b, num_classes] fp32."""
    x = patchify(cfg, images).astype(cfg.dtype)
    x = x @ params["patch_embed"].astype(cfg.dtype) \
        + params["patch_bias"].astype(cfg.dtype)
    if cfg.use_cls_token:
        cls = jnp.broadcast_to(
            params["cls_token"].astype(cfg.dtype),
            (x.shape[0], 1, cfg.hidden_size))
        x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(cfg.dtype)
    x = wsc(x, ("batch", "seq", "act_embed"))

    block_fn = lambda x, lp: (_block(cfg, x, lp), None)
    if cfg.remat:
        block_fn = jax.checkpoint(block_fn)
    x, _ = jax.lax.scan(block_fn, x, params["blocks"])

    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], cfg.norm_eps)
    pooled = x[:, 0] if cfg.use_cls_token else jnp.mean(x, axis=1)
    logits = (pooled.astype(jnp.float32)
              @ params["head_w"].astype(jnp.float32)
              + params["head_b"].astype(jnp.float32))
    return logits
