"""MNIST MLP — the CPU smoke config (BASELINE "MNIST MLP (JAX-CPU) smoke").

The reference's jupyter-scipy image exists to run exactly this kind of
small CPU workload in a notebook pod
(`/root/reference/components/example-notebook-servers/README.md:13-42`);
this module is the framework-native equivalent the smoke test launches.

Data: reads an `.npz` (keys: x_train/y_train/x_test/y_test) from
`KFTPU_MNIST_PATH` if set; otherwise generates a deterministic synthetic
digit-blob dataset (zero-egress environments have no downloader).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Iterator

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    input_dim: int = 784
    hidden_dims: tuple[int, ...] = (512, 512)
    num_classes: int = 10


MNIST_MLP = MLPConfig()


def param_logical_axes(cfg: MLPConfig) -> Params:
    layers = []
    for _ in cfg.hidden_dims:
        layers.append({"w": ("embed", "mlp"), "b": ("mlp",)})
    return {
        "layers": layers,
        "out_w": ("embed", "vocab"),
        "out_b": ("vocab",),
    }


def init(rng: jax.Array, cfg: MLPConfig = MNIST_MLP) -> Params:
    dims = (cfg.input_dim, *cfg.hidden_dims)
    keys = jax.random.split(rng, len(dims))
    layers = []
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        layers.append({
            "w": jax.random.normal(keys[i], (d_in, d_out)) * (d_in ** -0.5),
            "b": jnp.zeros((d_out,)),
        })
    return {
        "layers": layers,
        "out_w": jax.random.normal(keys[-1], (dims[-1], cfg.num_classes))
        * (dims[-1] ** -0.5),
        "out_b": jnp.zeros((cfg.num_classes,)),
    }


def apply(params: Params, x: jnp.ndarray) -> jnp.ndarray:
    """[b, 784] → logits [b, 10]."""
    h = x
    for layer in params["layers"]:
        h = jax.nn.relu(h @ layer["w"] + layer["b"])
    return h @ params["out_w"] + params["out_b"]


def loss_and_accuracy(params: Params, x, y) -> tuple[jnp.ndarray, jnp.ndarray]:
    logits = apply(params, x)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
    loss = jnp.mean(logz - gold)
    acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
    return loss, acc


def load_dataset(n_train: int = 4096, n_test: int = 512, seed: int = 0):
    """(x_train, y_train, x_test, y_test) float32 [N,784] / int32 [N]."""
    path = os.environ.get("KFTPU_MNIST_PATH", "")
    if path and os.path.exists(path):
        d = np.load(path)
        return (
            d["x_train"].reshape(len(d["x_train"]), -1).astype(np.float32) / 255.0,
            d["y_train"].astype(np.int32),
            d["x_test"].reshape(len(d["x_test"]), -1).astype(np.float32) / 255.0,
            d["y_test"].astype(np.int32),
        )
    # Synthetic stand-in: 10 gaussian class prototypes + noise. Linearly
    # separable enough that a learning bug shows as low accuracy.
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(10, 784)).astype(np.float32)

    def gen(n):
        y = rng.integers(0, 10, n).astype(np.int32)
        x = protos[y] + rng.normal(scale=2.0, size=(n, 784)).astype(np.float32)
        return x, y

    x_tr, y_tr = gen(n_train)
    x_te, y_te = gen(n_test)
    return x_tr, y_tr, x_te, y_te


def batches(x: np.ndarray, y: np.ndarray, batch_size: int,
            seed: int = 0) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    if batch_size > len(x):
        raise ValueError(
            f"batch_size {batch_size} exceeds dataset size {len(x)}")
    idx = np.random.default_rng(seed).permutation(len(x))
    for i in range(0, len(x) - batch_size + 1, batch_size):
        j = idx[i:i + batch_size]
        yield x[j], y[j]


def train_smoke(steps: int = 100, batch_size: int = 128,
                lr: float = 0.1) -> dict[str, float]:
    """The end-to-end CPU smoke: SGD for `steps`, returns metrics."""
    x_tr, y_tr, x_te, y_te = load_dataset()
    params = init(jax.random.key(0))

    @jax.jit
    def step(params, x, y):
        (loss, _), grads = jax.value_and_grad(
            loss_and_accuracy, has_aux=True)(params, x, y)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    n_done = 0
    epoch = 0
    while n_done < steps:
        for xb, yb in batches(x_tr, y_tr, batch_size, seed=epoch):
            params, loss = step(params, jnp.asarray(xb), jnp.asarray(yb))
            n_done += 1
            if n_done >= steps:
                break
        epoch += 1
    test_loss, test_acc = loss_and_accuracy(
        params, jnp.asarray(x_te), jnp.asarray(y_te))
    return {
        "final_train_loss": float(loss),
        "test_loss": float(test_loss),
        "test_accuracy": float(test_acc),
    }
