"""Model families: Llama-3 (dense, pipelined, MoE), Gemma, ViT, MLP.

Models are functional JAX: `init(rng, cfg) -> params pytree` plus
`apply(params, cfg, ...) -> logits`, with a parallel pytree of logical
axis names for sharding (kubeflow_tpu.parallel.sharding). No module
framework on the hot path — pytrees + pure functions keep tracing cheap
and sharding explicit.
"""
