"""Llama-3 family, TPU-first.

Functional implementation: parameters are a nested dict pytree with all
transformer blocks *stacked* on a leading "layers" axis so the forward
pass is a single `jax.lax.scan` over layers — one trace/compile of the
block regardless of depth, which keeps XLA compile time flat and lets
`jax.checkpoint` rematerialize per-block (HBM-for-FLOPs trade per
SURVEY.md §2b / pallas guide).

Sharding: every param leaf has logical axes (see `param_logical_axes`);
the FSDP/TP layout comes from kubeflow_tpu.parallel.sharding rules, not
from the model code.

Reference parity note: the reference control plane launches notebooks that
*run* models but contains none (SURVEY.md §2b). This module provides the
flagship model for BASELINE.json config "Llama-3-8B FSDP via
jax.distributed on v5e-16".
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.ops.embedding import embed_lookup
from jax.ad_checkpoint import checkpoint_name

from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import apply_rope, rope_frequencies
from kubeflow_tpu.parallel.sharding import with_sharding_constraint as wsc

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    # Sliding-window attention (Mistral-style): each position attends
    # its last `sliding_window` tokens. None = full causal. Applied to
    # every layer; both the XLA and Pallas paths honor it, and the
    # flash kernel skips out-of-band blocks entirely.
    sliding_window: int | None = None
    dtype: Any = jnp.bfloat16      # activation dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    # What the per-block jax.checkpoint keeps (HBM) vs recomputes (FLOPs):
    #   "full" — keep only block boundaries; bwd reruns the whole block
    #            fwd (~+2N matmul FLOPs, the classic 8N/6N = 33% tax).
    #   "mlp"  — additionally keep the three MLP matmul outputs
    #            (gate/up/down — 82% of a block's matmul FLOPs at Llama
    #            shapes) so bwd only reruns the attention side.
    #   "dots" — keep every matmul output (jax dots_with_no_batch_dims
    #            policy); bwd reruns just elementwise + the flash kernel.
    # Picked per preset by HBM headroom: chunked CE (train.trainer) freed
    # the logit tensor, which is what makes "mlp"/"dots" fit on one chip.
    remat_policy: str = "full"

    def __post_init__(self):
        if self.remat_policy not in _REMAT_POLICIES:
            raise ValueError(
                f"remat_policy {self.remat_policy!r} unknown "
                f"(choose from {sorted(_REMAT_POLICIES)})")

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim


# Remat save-policies, keyed by LlamaConfig.remat_policy (factories so
# import never touches jax state).
_REMAT_POLICIES = {
    "full": lambda: None,
    "mlp": lambda: jax.checkpoint_policies.save_only_these_names(
        "mlp_gate", "mlp_up", "mlp_down"),
    "dots": lambda: jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}


# BASELINE.json flagship + scaled-down siblings for single-chip benches and
# CPU tests. Sizes follow the Llama-3 family shape recipe.
LLAMA3_8B = LlamaConfig()
LLAMA3_1B = LlamaConfig(
    hidden_size=2048, intermediate_size=8192, num_layers=16,
    num_heads=16, num_kv_heads=8, head_dim=128,
)
LLAMA_TINY = LlamaConfig(
    vocab_size=512, hidden_size=128, intermediate_size=384, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=32, dtype=jnp.float32, remat=False,
)

CONFIGS = {"llama3-8b": LLAMA3_8B, "llama3-1b": LLAMA3_1B, "tiny": LLAMA_TINY}


def param_logical_axes(cfg: LlamaConfig) -> Params:
    """Logical axis names per param leaf (layers axis leads block params)."""
    block = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),     # [L, D, n_q * hd]
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed"),
        "w_gate": ("layers", "embed", "mlp"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    }
    axes: Params = {
        "embed": ("vocab", "embed"),
        "blocks": block,
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init(rng: jax.Array, cfg: LlamaConfig) -> Params:
    """Initialize params (truncated-normal fan-in scaling)."""
    keys = iter(jax.random.split(rng, 16))
    pd = cfg.param_dtype

    def dense(key, shape, fan_in):
        return (jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(pd)

    L, D = cfg.num_layers, cfg.hidden_size
    params: Params = {
        "embed": dense(next(keys), (cfg.vocab_size, D), D),
        "blocks": {
            "attn_norm": jnp.zeros((L, D), pd),
            "wq": dense(next(keys), (L, D, cfg.q_dim), D),
            "wk": dense(next(keys), (L, D, cfg.kv_dim), D),
            "wv": dense(next(keys), (L, D, cfg.kv_dim), D),
            "wo": dense(next(keys), (L, cfg.q_dim, D), cfg.q_dim),
            "mlp_norm": jnp.zeros((L, D), pd),
            "w_gate": dense(next(keys), (L, D, cfg.intermediate_size), D),
            "w_up": dense(next(keys), (L, D, cfg.intermediate_size), D),
            "w_down": dense(next(keys), (L, cfg.intermediate_size, D),
                            cfg.intermediate_size),
        },
        "final_norm": jnp.zeros((D,), pd),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(next(keys), (D, cfg.vocab_size), D)
    return params


def _attention_half(cfg, x, p, positions, inv_freq, kv_mask,
                    contiguous_positions=False):
    """Attention sub-block + residual (shared by the dense, pipelined,
    and MoE models — cfg needs the llama attention attrs only)."""
    b, s, D = x.shape
    h = rms_norm(x, p["attn_norm"], cfg.norm_eps)
    q = (h @ p["wq"].astype(cfg.dtype)).reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = (h @ p["wk"].astype(cfg.dtype)).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    v = (h @ p["wv"].astype(cfg.dtype)).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    q = apply_rope(q, positions, inv_freq)
    k = apply_rope(k, positions, inv_freq)
    q = wsc(q, ("batch", "seq", "act_heads", None))
    k = wsc(k, ("batch", "seq", "act_kv_heads", None))
    attn = dot_product_attention(q, k, v, positions, positions,
                                 causal=True, kv_mask=kv_mask,
                                 window=cfg.sliding_window,
                                 contiguous_positions=contiguous_positions)
    attn = attn.reshape(b, s, cfg.q_dim)
    x = x + attn @ p["wo"].astype(cfg.dtype)
    return wsc(x, ("batch", "seq", "act_embed"))


def _block(cfg: LlamaConfig, x, layer_params, positions, inv_freq, kv_mask,
           contiguous_positions=False):
    """One transformer block. x: [b, s, D] in cfg.dtype."""
    p = layer_params
    x = _attention_half(cfg, x, p, positions, inv_freq, kv_mask,
                        contiguous_positions)

    h = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    # checkpoint_name is inert unless cfg.remat_policy == "mlp" selects
    # these tensors as the save set (see _REMAT_POLICIES).
    gate = jax.nn.silu(
        checkpoint_name(h @ p["w_gate"].astype(cfg.dtype), "mlp_gate"))
    up = checkpoint_name(h @ p["w_up"].astype(cfg.dtype), "mlp_up")
    ff = wsc(gate * up, ("batch", "seq", "act_mlp"))
    x = x + checkpoint_name(ff @ p["w_down"].astype(cfg.dtype), "mlp_down")
    return wsc(x, ("batch", "seq", "act_embed"))


# Mesh-aware lookup (gather on trivial meshes, one-hot MXU contraction
# under sharding) now lives in ops.embedding — serving shares it.
_embed_lookup = embed_lookup


def hidden(
    params: Params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,              # [b, s] int32
    positions: jnp.ndarray | None = None,
    kv_mask: jnp.ndarray | None = None,  # [b, s] bool, False = padding
) -> jnp.ndarray:
    """Forward pass through the blocks → final NORMED hidden [b, s, D]
    in cfg.dtype. Callers that don't need full logits (the chunked-CE
    training loss) stop here; `apply` adds the unembedding."""
    b, s = tokens.shape
    contiguous = positions is None  # safe to use index-masked flash kernel
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    inv_freq = rope_frequencies(cfg.head_dim, theta=cfg.rope_theta)

    x = _embed_lookup(params["embed"], tokens, cfg.dtype)
    x = wsc(x, ("batch", "seq", "act_embed"))

    block_fn = lambda x, lp: (
        _block(cfg, x, lp, positions, inv_freq, kv_mask,
               contiguous_positions=contiguous), None)
    if cfg.remat:
        block_fn = jax.checkpoint(
            block_fn, policy=_REMAT_POLICIES[cfg.remat_policy]())
    x, _ = jax.lax.scan(block_fn, x, params["blocks"])

    return rms_norm(x, params["final_norm"], cfg.norm_eps)


def unembed_matrix(params: Params, cfg: LlamaConfig) -> jnp.ndarray:
    """[D, vocab] unembedding (the tied table transposed, or lm_head)."""
    return params["embed"].T if cfg.tie_embeddings else params["lm_head"]


def apply(
    params: Params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,              # [b, s] int32
    positions: jnp.ndarray | None = None,
    kv_mask: jnp.ndarray | None = None,  # [b, s] bool, False = padding
) -> jnp.ndarray:
    """Forward pass → logits [b, s, vocab] (fp32)."""
    x = hidden(params, cfg, tokens, positions, kv_mask)
    head = unembed_matrix(params, cfg)
    logits = x.astype(jnp.float32) @ head.astype(jnp.float32)
    return wsc(logits, ("batch", "seq", "act_vocab"))


def num_params(cfg: LlamaConfig) -> int:
    shapes = jax.eval_shape(lambda k: init(k, cfg),
                            jax.ShapeDtypeStruct((2,), jnp.uint32))
    return sum(int(jnp.prod(jnp.array(l.shape))) for l in jax.tree.leaves(shapes))
