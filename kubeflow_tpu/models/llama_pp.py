"""Pipeline-parallel Llama: transformer blocks as GPipe stages.

VERDICT r1 weak #5: the pipeline was only exercised with toy identity
stages. This composes it with the flagship model: the stacked-layer
block params (leaves [L, ...]) reshape to [S, L/S, ...] — S pipeline
stages of L/S layers each — and each stage scans its own layers exactly
like the non-PP forward scans all of them. Embedding and the unembed
projection stay OUTSIDE the pipeline (they are not shape-preserving;
ref SURVEY.md §2b PP row), computed replicated across the stage axis.

Numerics: stage-partitioned scan ∘ pipeline schedule ≡ the full-depth
scan, so PP logits match `llama.apply` exactly up to float re-association
(tested in tests/test_llama_pp.py), and the whole thing is differentiable
— grads for each stage's blocks stay resident on that stage's devices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.models import llama
from kubeflow_tpu.models.llama import LlamaConfig, Params
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import rope_frequencies
from kubeflow_tpu.parallel import mesh as mesh_lib
from kubeflow_tpu.parallel import pipeline as pp
from kubeflow_tpu.train import trainer as trainer_lib


def split_stages(params: Params, cfg: LlamaConfig, n_stages: int) -> Params:
    """Blocks [L, ...] → [S, L/S, ...] (stage-major). Embed/head pass
    through untouched."""
    if cfg.num_layers % n_stages:
        raise ValueError(
            f"num_layers={cfg.num_layers} not divisible by "
            f"n_stages={n_stages}"
        )
    per = cfg.num_layers // n_stages
    return jax.tree.map(
        lambda leaf: leaf.reshape(n_stages, per, *leaf.shape[1:]),
        params["blocks"],
    )


def merge_stages(staged_blocks: Params) -> Params:
    """Inverse of split_stages (for checkpoint interop)."""
    return jax.tree.map(
        lambda leaf: leaf.reshape(-1, *leaf.shape[2:]), staged_blocks
    )


def apply_pipelined(
    params: Params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,          # [b, s] int32
    mesh: Mesh,
    *,
    stage_axis: str = "stage",
    num_microbatches: int | None = None,
) -> jnp.ndarray:
    """Forward pass with blocks pipelined over `stage_axis` → logits.

    Microbatch count defaults to 2x the stage count (the GPipe
    efficiency knob: bubble fraction is (S-1)/(M+S-1))."""
    S = mesh.shape[stage_axis]
    M = num_microbatches or 2 * S
    b, s = tokens.shape
    if b % M:
        raise ValueError(f"batch {b} not divisible by microbatches {M}")
    mb = b // M
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (mb, s))
    inv_freq = rope_frequencies(cfg.head_dim, theta=cfg.rope_theta)

    def stage_fn(stage_blocks: Params, x: jnp.ndarray) -> jnp.ndarray:
        def blk(x, lp):
            return llama._block(
                cfg, x, lp, positions, inv_freq, None,
                contiguous_positions=True,
            ), None

        if cfg.remat:
            blk = jax.checkpoint(
                blk, policy=llama._REMAT_POLICIES[cfg.remat_policy]())
        x, _ = jax.lax.scan(blk, x, stage_blocks)
        return x

    x = llama._embed_lookup(params["embed"], tokens, cfg.dtype)
    y = pp.pipeline_sharded(
        stage_fn,
        split_stages(params, cfg, S),
        x,
        mesh,
        stage_axis=stage_axis,
        num_microbatches=M,
    )
    y = rms_norm(y, params["final_norm"], cfg.norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    return y.astype(jnp.float32) @ head.astype(jnp.float32)


def loss_pipelined(params, cfg, tokens, targets, mesh, **kw) -> jnp.ndarray:
    logits = apply_pipelined(params, cfg, tokens, mesh, **kw)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


class PipelineTrainer:
    """PP composed with the real training stack.

    The same optimizer chain as `train.Trainer` (warmup-cosine AdamW +
    global-norm clip, `trainer.make_optimizer`) stepping the pipelined
    Llama forward on a (stage, data) mesh. Residency follows GPipe
    semantics: block params — and their Adam moments, via the Trainer's
    path-matched opt-state sharding — shard over `stage_axis` along the
    layer dim (the contiguous stage-major split that `split_stages`
    reshapes without data movement); the batch shards over `data_axis`,
    which stays a GSPMD-auto axis inside the pipeline's shard_map so
    XLA inserts the data-parallel gradient reductions.
    """

    def __init__(
        self,
        cfg: LlamaConfig,
        mesh: Mesh,
        *,
        stage_axis: str = "stage",
        data_axis: str = "data",
        num_microbatches: int | None = None,
        train_config: trainer_lib.TrainConfig = trainer_lib.TrainConfig(),
    ):
        S = mesh.shape[stage_axis]
        if cfg.num_layers % S:
            raise ValueError(
                f"num_layers={cfg.num_layers} not divisible by "
                f"{stage_axis}={S}"
            )
        self.cfg = cfg
        self.mesh = mesh
        self.stage_axis = stage_axis
        self.data_axis = data_axis
        self.num_microbatches = num_microbatches or 2 * S
        self.tc = train_config
        self.optimizer = trainer_lib.make_optimizer(train_config)

        params_shapes = jax.eval_shape(
            lambda k: llama.init(k, cfg),
            jax.ShapeDtypeStruct((2,), jnp.uint32),
        )

        def pick(path, leaf):
            keys = tuple(getattr(p, "key", "") for p in path)
            spec = P(stage_axis) if "blocks" in keys else P()
            return NamedSharding(mesh, spec)

        self.param_shardings = jax.tree_util.tree_map_with_path(
            pick, params_shapes
        )
        opt_shapes = jax.eval_shape(self.optimizer.init, params_shapes)
        self.opt_shardings = trainer_lib._opt_state_shardings(
            opt_shapes, params_shapes, self.param_shardings, mesh
        )
        self.state_shardings = trainer_lib.TrainState(
            self.param_shardings, self.opt_shardings,
            NamedSharding(mesh, P()),
        )
        self.batch_sharding = NamedSharding(mesh, P(data_axis))
        self._jit_init = jax.jit(
            self._init, out_shardings=self.state_shardings
        )
        self._jit_step = jax.jit(
            self._step,
            in_shardings=(self.state_shardings, self.batch_sharding,
                          self.batch_sharding),
            out_shardings=(self.state_shardings,
                           NamedSharding(self.mesh, P())),
            donate_argnums=(0,),
        )

    def _init(self, rng: jax.Array) -> trainer_lib.TrainState:
        params = llama.init(rng, self.cfg)
        return trainer_lib.TrainState(
            params, self.optimizer.init(params), jnp.zeros((), jnp.int32)
        )

    def _step(self, state: trainer_lib.TrainState, tokens, targets):
        def loss_fn(params):
            logits = apply_pipelined(
                params, self.cfg, tokens, self.mesh,
                stage_axis=self.stage_axis,
                num_microbatches=self.num_microbatches,
            )
            return trainer_lib.cross_entropy_loss(logits, targets)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = self.optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return (
            trainer_lib.TrainState(params, opt_state, state.step + 1),
            loss,
        )

    def init(self, rng: jax.Array) -> trainer_lib.TrainState:
        with mesh_lib.set_mesh(self.mesh):
            return self._jit_init(rng)

    def step(self, state: trainer_lib.TrainState, tokens, targets):
        with mesh_lib.set_mesh(self.mesh):
            return self._jit_step(state, tokens, targets)


def make_train_step(cfg: LlamaConfig, mesh: Mesh, learning_rate: float = 1e-3,
                    **kw):
    """SGD-with-momentum train step over the pipelined loss — enough to
    prove PP trains (grads flow through scan + ppermute); production
    training composes apply_pipelined into the Trainer's optimizer via
    `PipelineTrainer`."""

    @jax.jit
    def step(params, momentum, tokens, targets):
        loss, grads = jax.value_and_grad(
            lambda p: loss_pipelined(p, cfg, tokens, targets, mesh, **kw)
        )(params)
        momentum = jax.tree.map(
            lambda m, g: 0.9 * m + g, momentum, grads
        )
        params = jax.tree.map(
            lambda p, m: (p - learning_rate * m.astype(p.dtype)), params,
            momentum,
        )
        return params, momentum, loss

    return step
