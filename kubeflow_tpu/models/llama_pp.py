"""Pipeline-parallel Llama: transformer blocks as GPipe stages.

VERDICT r1 weak #5: the pipeline was only exercised with toy identity
stages. This composes it with the flagship model: the stacked-layer
block params (leaves [L, ...]) reshape to [S, L/S, ...] — S pipeline
stages of L/S layers each — and each stage scans its own layers exactly
like the non-PP forward scans all of them. Embedding and the unembed
projection stay OUTSIDE the pipeline (they are not shape-preserving;
ref SURVEY.md §2b PP row), computed replicated across the stage axis.

Numerics: stage-partitioned scan ∘ pipeline schedule ≡ the full-depth
scan, so PP logits match `llama.apply` exactly up to float re-association
(tested in tests/test_llama_pp.py), and the whole thing is differentiable
— grads for each stage's blocks stay resident on that stage's devices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from kubeflow_tpu.models import llama
from kubeflow_tpu.models.llama import LlamaConfig, Params
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import rope_frequencies
from kubeflow_tpu.parallel import pipeline as pp


def split_stages(params: Params, cfg: LlamaConfig, n_stages: int) -> Params:
    """Blocks [L, ...] → [S, L/S, ...] (stage-major). Embed/head pass
    through untouched."""
    if cfg.num_layers % n_stages:
        raise ValueError(
            f"num_layers={cfg.num_layers} not divisible by "
            f"n_stages={n_stages}"
        )
    per = cfg.num_layers // n_stages
    return jax.tree.map(
        lambda leaf: leaf.reshape(n_stages, per, *leaf.shape[1:]),
        params["blocks"],
    )


def merge_stages(staged_blocks: Params) -> Params:
    """Inverse of split_stages (for checkpoint interop)."""
    return jax.tree.map(
        lambda leaf: leaf.reshape(-1, *leaf.shape[2:]), staged_blocks
    )


def apply_pipelined(
    params: Params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,          # [b, s] int32
    mesh: Mesh,
    *,
    stage_axis: str = "stage",
    num_microbatches: int | None = None,
) -> jnp.ndarray:
    """Forward pass with blocks pipelined over `stage_axis` → logits.

    Microbatch count defaults to 2x the stage count (the GPipe
    efficiency knob: bubble fraction is (S-1)/(M+S-1))."""
    S = mesh.shape[stage_axis]
    M = num_microbatches or 2 * S
    b, s = tokens.shape
    if b % M:
        raise ValueError(f"batch {b} not divisible by microbatches {M}")
    mb = b // M
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (mb, s))
    inv_freq = rope_frequencies(cfg.head_dim, theta=cfg.rope_theta)

    def stage_fn(stage_blocks: Params, x: jnp.ndarray) -> jnp.ndarray:
        def blk(x, lp):
            return llama._block(
                cfg, x, lp, positions, inv_freq, None,
                contiguous_positions=True,
            ), None

        if cfg.remat:
            blk = jax.checkpoint(blk)
        x, _ = jax.lax.scan(blk, x, stage_blocks)
        return x

    x = llama._embed_lookup(params["embed"], tokens, cfg.dtype)
    y = pp.pipeline_sharded(
        stage_fn,
        split_stages(params, cfg, S),
        x,
        mesh,
        stage_axis=stage_axis,
        num_microbatches=M,
    )
    y = rms_norm(y, params["final_norm"], cfg.norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"])
    return y.astype(jnp.float32) @ head.astype(jnp.float32)


def loss_pipelined(params, cfg, tokens, targets, mesh, **kw) -> jnp.ndarray:
    logits = apply_pipelined(params, cfg, tokens, mesh, **kw)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def make_train_step(cfg: LlamaConfig, mesh: Mesh, learning_rate: float = 1e-3,
                    **kw):
    """SGD-with-momentum train step over the pipelined loss — enough to
    prove PP trains (grads flow through scan + ppermute); production
    training composes apply_pipelined into the Trainer's optimizer."""

    @jax.jit
    def step(params, momentum, tokens, targets):
        loss, grads = jax.value_and_grad(
            lambda p: loss_pipelined(p, cfg, tokens, targets, mesh, **kw)
        )(params)
        momentum = jax.tree.map(
            lambda m, g: 0.9 * m + g, momentum, grads
        )
        params = jax.tree.map(
            lambda p, m: (p - learning_rate * m.astype(p.dtype)), params,
            momentum,
        )
        return params, momentum, loss

    return step
