"""Mixtral-style MoE transformer: Llama attention + expert FFN blocks.

The dense Llama block's SwiGLU MLP is replaced by parallel.moe's top-k
routed expert layer; everything else (GQA attention, rope, rms norms,
stacked-layer `lax.scan`, per-block remat) is the Llama recipe. The
Switch-style load-balancing auxiliary loss accumulates through the
layer scan and comes back next to the logits so the training loss can
weight it (`aux_loss_weight`).

TPU notes: expert weights are stacked [L, E, ...] so the same scan
slices per-layer expert tables; the "experts" logical axis shards over
tensor by default (parallel/sharding.py) and composes with EP via
moe.moe_mlp_expert_parallel for explicit all-to-all meshes.

Causality caveat (inherent to capacity-based MoE, not a bug): when an
expert overflows its capacity, slot assignment is rank-major (Switch
convention — every token's PRIMARY choice outranks any secondary), so
a later token can evict an earlier token's secondary route and
train-time logits are only causal while capacity holds. For strictly
causal evaluation/decoding, raise `capacity_factor` so nothing drops
(capacity >= tokens * top_k / num_experts guarantees it).

Reference parity: none — the reference has no models (SURVEY.md §2b);
this extends the model-family roster the way Mixtral extends Llama.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.llama import _attention_half
from kubeflow_tpu.ops.embedding import embed_lookup
from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import rope_frequencies
from kubeflow_tpu.parallel import moe as moe_lib
from kubeflow_tpu.parallel.sharding import with_sharding_constraint as wsc

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoELlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # MoE
    num_experts: int = 8
    top_k: int = 2
    expert_mlp_dim: int = 14336     # per-expert SwiGLU hidden
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    sliding_window: int | None = None   # llama.py semantics

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    def moe_config(self) -> moe_lib.MoEConfig:
        return moe_lib.MoEConfig(
            num_experts=self.num_experts, top_k=self.top_k,
            embed_dim=self.hidden_size, mlp_dim=self.expert_mlp_dim,
            capacity_factor=self.capacity_factor, dtype=self.dtype)


MIXTRAL_TINY = MoELlamaConfig(
    vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
    num_kv_heads=2, head_dim=32, num_experts=4, top_k=2,
    expert_mlp_dim=192, dtype=jnp.float32, remat=False)


def init(rng: jax.Array, cfg: MoELlamaConfig) -> Params:
    keys = iter(jax.random.split(rng, 16))
    pd = cfg.param_dtype
    L, D, E, M = (cfg.num_layers, cfg.hidden_size, cfg.num_experts,
                  cfg.expert_mlp_dim)

    def dense(key, shape, fan_in):
        return (jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(pd)

    return {
        "embed": dense(next(keys), (cfg.vocab_size, D), D),
        "blocks": {
            "attn_norm": jnp.zeros((L, D), pd),
            "wq": dense(next(keys), (L, D, cfg.q_dim), D),
            "wk": dense(next(keys), (L, D, cfg.kv_dim), D),
            "wv": dense(next(keys), (L, D, cfg.kv_dim), D),
            "wo": dense(next(keys), (L, cfg.q_dim, D), cfg.q_dim),
            "mlp_norm": jnp.zeros((L, D), pd),
            "router": dense(next(keys), (L, D, E), D),
            "w_gate": dense(next(keys), (L, E, D, M), D),
            "w_up": dense(next(keys), (L, E, D, M), D),
            "w_down": dense(next(keys), (L, E, M, D), M),
        },
        "final_norm": jnp.zeros((D,), pd),
        "lm_head": dense(next(keys), (D, cfg.vocab_size), D),
    }


def param_logical_axes(cfg: MoELlamaConfig) -> Params:
    block = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", "embed"),
        "router": ("layers", "embed", None),
        "w_gate": ("layers", "experts", "embed", None),
        "w_up": ("layers", "experts", "embed", None),
        "w_down": ("layers", "experts", None, "embed"),
    }
    return {
        "embed": ("vocab", "embed"),
        "blocks": block,
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def _block(cfg: MoELlamaConfig, x, p, positions, inv_freq):
    # the llama attention half verbatim (shared code — sliding_window,
    # GQA, sharding constraints all inherited)
    x = _attention_half(cfg, x, p, positions, inv_freq, None,
                        contiguous_positions=True)

    h = rms_norm(x, p["mlp_norm"], cfg.norm_eps)
    # cast expert weights to the ACTIVATION dtype: fp32 master params
    # fed raw would promote the expert einsums — the bulk of a MoE
    # block's FLOPs — to fp32
    moe_params = {
        name: p[name].astype(cfg.dtype)
        for name in ("router", "w_gate", "w_up", "w_down")
    }
    y, aux = moe_lib.moe_mlp(moe_params, h, cfg.moe_config())
    x = x + y
    return wsc(x, ("batch", "seq", "act_embed")), aux


def apply(
    params: Params,
    cfg: MoELlamaConfig,
    tokens: jnp.ndarray,                # [b, s] int32
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Forward pass → (logits [b, s, vocab] fp32, mean aux loss [])."""
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    inv_freq = rope_frequencies(cfg.head_dim, theta=cfg.rope_theta)

    x = embed_lookup(params["embed"], tokens, cfg.dtype)
    x = wsc(x, ("batch", "seq", "act_embed"))

    def blk(carry, lp):
        x, aux = carry
        x, a = _block(cfg, x, lp, positions, inv_freq)
        return (x, aux + a), None

    if cfg.remat:
        blk = jax.checkpoint(blk)
    (x, aux), _ = jax.lax.scan(
        blk, (x, jnp.zeros((), jnp.float32)), params["blocks"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = x.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)
    logits = wsc(logits, ("batch", "seq", "act_vocab"))
    return logits, aux / cfg.num_layers


def loss_fn(cfg: MoELlamaConfig):
    """Trainer-shaped loss: next-token CE + weighted load-balance aux."""
    from kubeflow_tpu.train.trainer import cross_entropy_loss

    def loss(params, tokens, targets, mask):
        logits, aux = apply(params, cfg, tokens)
        return (cross_entropy_loss(logits, targets, mask)
                + cfg.aux_loss_weight * aux)

    return loss
