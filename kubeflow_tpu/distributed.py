"""Multi-host bootstrap: consume the webhook's topology env.

The control plane's half of the collective backend already exists — the
admission webhook computes and injects `TPU_WORKER_ID`,
`TPU_WORKER_HOSTNAMES`, `JAX_COORDINATOR_ADDRESS` and
`KFTPU_NUM_PROCESSES` onto every gang pod
(controlplane/webhook.py:_inject_tpu_env). This module is the in-pod
half: it turns that env into a live `jax.distributed` process group —
the NCCL/MPI-rendezvous replacement SURVEY.md §5 names ("Distributed
communication backend": `jax.distributed.initialize(coordinator_address,
num_processes=len(TPU_WORKER_HOSTNAMES), process_id=TPU_WORKER_ID)`).
The reference's closest mechanism is env merging in its PodDefault
webhook (admission-webhook/main.go:153-188); it has no consumer because
it has no compute layer. Ours does: call `initialize_from_env()` first
thing in a training entrypoint — the jupyter-jax-tpu image wires this
to kernel start via its system IPython config
(images/jupyter-jax-tpu/ipython_config.py →
kubeflow_tpu.kernel_bootstrap.bootstrap) — then
`parallel.mesh_from_env()` for the sharding layout.

Collectives then ride ICI within a slice and DCN across slices — both
owned by XLA; nothing here opens a socket besides the coordinator
handshake.
"""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger(__name__)

COORDINATOR_ENV = "JAX_COORDINATOR_ADDRESS"
NUM_PROCESSES_ENV = "KFTPU_NUM_PROCESSES"
# Global process id. TPU_WORKER_ID is the fallback for single-slice
# gangs only: libtpu worker ids are PER SLICE, so in a multi-slice gang
# they repeat across slices and cannot serve as the jax.distributed
# process_id — the webhook injects KFTPU_PROCESS_ID (the global gang
# ordinal) for exactly that reason.
PROCESS_ID_ENV = "KFTPU_PROCESS_ID"
WORKER_ID_FALLBACK_ENV = "TPU_WORKER_ID"

_initialized = False


def is_initialized() -> bool:
    return _initialized


def initialize_from_env(timeout_secs: int | None = None) -> bool:
    """Form the global process group from webhook-injected env.

    Returns True when `jax.distributed.initialize` ran (multi-process
    gang), False when the env describes a single process (or is absent)
    and no initialization is needed — single-pod notebooks fall through
    to plain local JAX. Safe to call more than once; subsequent calls
    are no-ops.

    Raises ValueError on half-injected env (coordinator without process
    count, non-integer worker id) — a misconfigured gang should fail
    loudly at startup, not hang N-1 workers in the coordinator
    handshake.
    """
    global _initialized
    if _initialized:
        return True
    coordinator = os.environ.get(COORDINATOR_ENV, "")
    raw_num = os.environ.get(NUM_PROCESSES_ENV, "")
    raw_id = (os.environ.get(PROCESS_ID_ENV, "")
              or os.environ.get(WORKER_ID_FALLBACK_ENV, ""))
    if not coordinator and not raw_num:
        return False
    if not coordinator or not raw_num:
        raise ValueError(
            f"half-injected gang env: {COORDINATOR_ENV}={coordinator!r} "
            f"{NUM_PROCESSES_ENV}={raw_num!r} — the TPU webhook injects "
            "both or neither"
        )
    multi_slice = any(
        os.environ.get(v) not in (None, "", "1")
        for v in ("KFTPU_NUM_SLICES", "MEGASCALE_NUM_SLICES")
    )
    if multi_slice and not os.environ.get(PROCESS_ID_ENV):
        raise ValueError(
            f"multi-slice gang without {PROCESS_ID_ENV}: the per-slice "
            f"{WORKER_ID_FALLBACK_ENV} repeats across slices and cannot "
            "be the global process id"
        )
    try:
        num_processes = int(raw_num)
        process_id = int(raw_id or "0")
    except ValueError as e:
        raise ValueError(f"non-integer gang env: {e}") from e
    if num_processes <= 1:
        return False
    kwargs = {}
    if timeout_secs is not None:
        kwargs["initialization_timeout"] = timeout_secs
    log.info(
        "jax.distributed.initialize coordinator=%s process=%d/%d",
        coordinator, process_id, num_processes,
    )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    _initialized = True
    return True


def shutdown() -> None:
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def global_mesh_from_env(devices=None):
    """initialize_from_env() + parallel.mesh_from_env() in one call —
    the two-line prologue of every gang training script."""
    initialize_from_env()
    from kubeflow_tpu.parallel.mesh import mesh_from_env

    return mesh_from_env(devices)
