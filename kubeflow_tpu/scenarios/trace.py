"""Versioned, replayable traffic traces: the scenario interchange format.

One trace = one JSONL file. The FIRST line is the header object
(`{"trace": {...}}`) carrying the format version, the scenario name,
the seed that generated it (0 for recordings), free-form `meta`, and a
declarative `expect` block — the SLO outcomes a replay of this trace
must satisfy (see `kubeflow_tpu.scenarios.replay.check_expect`). Every
following line is one request:

    {"id": "r-000007", "at": 1.25, "prompt_tokens": 24, "max_new": 16,
     "tenant": "bulk", "priority": "batch", "prefix_group": "agent-3",
     "prefix_tokens": 16, "abandon_at": null}

- `at`            — arrival offset in seconds from trace start
                    (open-loop: the replayer fires at `at/speed`
                    regardless of how the target is coping),
- `prompt_tokens` — prompt LENGTH; actual token ids are derived
                    deterministically from (trace seed, prefix_group,
                    id) at replay time, so traces stay compact and a
                    recorded trace never ships user content,
- `prefix_group`  — requests sharing a group share their first
                    `prefix_tokens` prompt tokens, reproducing the
                    radix-cache reuse structure of agent swarms,
- `abandon_at`    — offset from trace start at which the client hangs
                    up (null = patient client); the replayer closes
                    the stream there, exercising the slot-release
                    cancellation path.

The writer is canonical — fixed key order, floats rounded at
construction — so write -> read -> write is byte-identical and traces
diff cleanly in review. Version gates reading: a major bump means the
field semantics changed and old readers must refuse, not guess.
"""

from __future__ import annotations

import dataclasses
import io
import json
from typing import Any

TRACE_VERSION = 1

# Canonical per-request key order (the writer emits exactly these, in
# this order; the reader tolerates unknown EXTRA keys for forward
# compat within a major version).
REQUEST_FIELDS = ("id", "at", "prompt_tokens", "max_new", "tenant",
                  "priority", "prefix_group", "prefix_tokens",
                  "abandon_at")

_TIME_DECIMALS = 6  # microsecond resolution; rounds at construction


def _t(v: float) -> float:
    """Canonical time value: rounded once, so the float that lives in
    the dataclass is the float JSON round-trips."""
    return round(float(v), _TIME_DECIMALS)


@dataclasses.dataclass
class TraceRequest:
    """One arrival. Frozen-by-convention: normalize in __post_init__,
    then treat as immutable."""

    id: str
    at: float
    prompt_tokens: int
    max_new: int
    tenant: str = ""
    priority: str = "standard"
    prefix_group: str = ""
    prefix_tokens: int = 0
    abandon_at: float | None = None

    def __post_init__(self) -> None:
        self.at = _t(self.at)
        if self.abandon_at is not None:
            self.abandon_at = _t(self.abandon_at)
        if self.at < 0:
            raise ValueError(f"request {self.id!r}: at {self.at} < 0")
        if self.prompt_tokens < 1:
            raise ValueError(
                f"request {self.id!r}: prompt_tokens must be >= 1")
        if self.max_new < 1:
            raise ValueError(
                f"request {self.id!r}: max_new must be >= 1")
        if not (0 <= self.prefix_tokens <= self.prompt_tokens):
            raise ValueError(
                f"request {self.id!r}: prefix_tokens "
                f"{self.prefix_tokens} outside [0, prompt_tokens]")
        if self.prefix_tokens and not self.prefix_group:
            raise ValueError(
                f"request {self.id!r}: prefix_tokens without a "
                "prefix_group")
        if self.abandon_at is not None and self.abandon_at < self.at:
            raise ValueError(
                f"request {self.id!r}: abandon_at {self.abandon_at} "
                f"before arrival {self.at}")

    def to_json(self) -> str:
        d = {k: getattr(self, k) for k in REQUEST_FIELDS}
        return json.dumps(d, separators=(",", ":"))

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TraceRequest":
        missing = [k for k in ("id", "at", "prompt_tokens", "max_new")
                   if k not in d]
        if missing:
            raise ValueError(f"trace request missing {missing}: {d}")
        return cls(**{k: d[k] for k in REQUEST_FIELDS if k in d})


@dataclasses.dataclass
class Trace:
    """Header + arrivals, sorted by (at, id) at construction so two
    traces with the same content serialize identically regardless of
    generation order."""

    name: str
    requests: list[TraceRequest]
    seed: int = 0
    generator: str = ""
    expect: dict[str, dict[str, float]] = dataclasses.field(
        default_factory=dict)
    meta: dict[str, Any] = dataclasses.field(default_factory=dict)
    version: int = TRACE_VERSION

    def __post_init__(self) -> None:
        if self.version != TRACE_VERSION:
            raise ValueError(
                f"trace version {self.version} unsupported (this "
                f"reader speaks version {TRACE_VERSION}); regenerate "
                "or upgrade")
        for k, bounds in self.expect.items():
            if not isinstance(bounds, dict):
                raise ValueError(
                    f"expect[{k!r}] must be a dict of bounds")
            bad = set(bounds) - {"min", "max"}
            if bad:
                raise ValueError(
                    f"expect[{k!r}] has unknown bound ops {sorted(bad)}"
                    " (only min/max)")
        self.requests = sorted(self.requests,
                               key=lambda r: (r.at, r.id))
        seen: set[str] = set()
        for r in self.requests:
            if r.id in seen:
                raise ValueError(f"duplicate request id {r.id!r}")
            seen.add(r.id)

    @property
    def duration_s(self) -> float:
        return self.requests[-1].at if self.requests else 0.0

    def header_json(self) -> str:
        return json.dumps({"trace": {
            "version": self.version,
            "name": self.name,
            "seed": self.seed,
            "generator": self.generator,
            "expect": self.expect,
            "meta": self.meta,
        }}, separators=(",", ":"), sort_keys=False)

    def dumps(self) -> str:
        buf = io.StringIO()
        buf.write(self.header_json() + "\n")
        for r in self.requests:
            buf.write(r.to_json() + "\n")
        return buf.getvalue()

    @classmethod
    def loads(cls, text: str) -> "Trace":
        lines = [ln for ln in text.splitlines() if ln.strip()]
        if not lines:
            raise ValueError("empty trace file")
        try:
            head = json.loads(lines[0])
        except json.JSONDecodeError as e:
            raise ValueError(f"trace header is not JSON: {e}") from None
        if not isinstance(head, dict) or "trace" not in head:
            raise ValueError(
                "first line must be the header object "
                '{"trace": {...}} — is this a scenario trace file?')
        h = head["trace"]
        version = h.get("version")
        if version != TRACE_VERSION:
            raise ValueError(
                f"trace version {version!r} unsupported (reader "
                f"speaks {TRACE_VERSION})")
        reqs = []
        for i, ln in enumerate(lines[1:], start=2):
            try:
                reqs.append(TraceRequest.from_dict(json.loads(ln)))
            except (json.JSONDecodeError, TypeError, ValueError) as e:
                raise ValueError(f"trace line {i}: {e}") from None
        return cls(name=h.get("name", ""), requests=reqs,
                   seed=int(h.get("seed", 0)),
                   generator=h.get("generator", ""),
                   expect=h.get("expect", {}) or {},
                   meta=h.get("meta", {}) or {},
                   version=version)


def write_trace(trace: Trace, path: str) -> None:
    with open(path, "w") as f:
        f.write(trace.dumps())


def read_trace(path: str) -> Trace:
    with open(path) as f:
        return Trace.loads(f.read())
