"""Trace-driven scenario engine: record, generate, and replay
production traffic shapes against any serving target.

One trace format (`trace.py`), deterministic-seeded generators for the
shapes that break schedulers (`generate.py`), a recorder that captures
any live run off the timeline store (`record.py`), and an open-loop
replayer with declarative SLO assertions (`replay.py`). The loadtest's
`--mode scenario` and `python -m kubeflow_tpu.scenarios` are the two
front doors.
"""

from kubeflow_tpu.scenarios.generate import GENERATORS, generate
from kubeflow_tpu.scenarios.record import (
    record_from_server,
    trace_from_store,
    trace_from_timeline_payloads,
)
from kubeflow_tpu.scenarios.replay import (
    HttpTarget,
    assert_expect,
    check_expect,
    prompt_ids_for,
    replay,
    summarize,
)
from kubeflow_tpu.scenarios.trace import (
    TRACE_VERSION,
    Trace,
    TraceRequest,
    read_trace,
    write_trace,
)

__all__ = [
    "TRACE_VERSION", "Trace", "TraceRequest", "read_trace",
    "write_trace", "GENERATORS", "generate", "record_from_server",
    "trace_from_store", "trace_from_timeline_payloads", "HttpTarget",
    "assert_expect", "check_expect", "prompt_ids_for", "replay",
    "summarize",
]
