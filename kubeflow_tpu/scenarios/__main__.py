"""CLI for the scenario engine.

    # generate a seeded scenario file
    python -m kubeflow_tpu.scenarios generate flash-crowd \
        --seed 7 --out flash.jsonl --param burst_rps=20

    # replay it against any live serving endpoint (replica or router)
    python -m kubeflow_tpu.scenarios replay flash.jsonl \
        --target http://127.0.0.1:8000 --model tiny --assert-expect

    # capture a live run into a replayable trace
    python -m kubeflow_tpu.scenarios record \
        --target http://127.0.0.1:8000 --out captured.jsonl

    # inspect a trace without replaying it
    python -m kubeflow_tpu.scenarios describe flash.jsonl

`replay` prints one JSON result line (the same dict the `expect`
block is judged against); `--assert-expect` exits nonzero on a
violated bound, which is what `make scenario-check` gates on.
"""

from __future__ import annotations

import argparse
import json
import sys

from kubeflow_tpu.scenarios.generate import GENERATORS
from kubeflow_tpu.scenarios.generate import generate as generate_trace
from kubeflow_tpu.scenarios.record import record_from_server
from kubeflow_tpu.scenarios.replay import (
    HttpTarget,
    check_expect,
    replay,
    summarize,
)
from kubeflow_tpu.scenarios.trace import read_trace, write_trace


def _parse_params(pairs: list[str]) -> dict:
    """--param k=v with JSON-typed values (bare words stay strings)."""
    out = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"--param needs k=v, got {pair!r}")
        k, v = pair.split("=", 1)
        try:
            out[k] = json.loads(v)
        except json.JSONDecodeError:
            out[k] = v
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="kubeflow_tpu.scenarios")
    sub = p.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("generate", help="write a seeded scenario file")
    g.add_argument("shape", choices=sorted(GENERATORS),
                   type=lambda s: s.replace("-", "_"))
    g.add_argument("--seed", type=int, required=True,
                   help="explicit seed — same seed, byte-identical "
                        "file, no wall-clock defaults")
    g.add_argument("--out", required=True)
    g.add_argument("--param", action="append", default=[],
                   help="generator kwarg override, k=v (JSON values)")

    r = sub.add_parser("replay", help="drive a live target with a trace")
    r.add_argument("trace")
    r.add_argument("--target", required=True,
                   help="base URL of a serving replica or fleet router")
    r.add_argument("--model", default="tiny")
    r.add_argument("--speed", type=float, default=1.0,
                   help="time-scale: 2.0 fires arrivals twice as fast")
    r.add_argument("--assert-expect", action="store_true",
                   help="exit 1 if the trace's expect block is violated")

    c = sub.add_parser("record", help="capture a live run into a trace")
    c.add_argument("--target", required=True)
    c.add_argument("--out", required=True)
    c.add_argument("--name", default="recorded")
    c.add_argument("--ids-file", default="",
                   help="newline-separated request ids to capture "
                        "(default: enumerate /v1/requests/timelines)")

    d = sub.add_parser("describe", help="summarize a trace file")
    d.add_argument("trace")

    args = p.parse_args(argv)

    if args.cmd == "generate":
        tr = generate_trace(args.shape, args.seed,
                              **_parse_params(args.param))
        write_trace(tr, args.out)
        print(json.dumps({"written": args.out, "name": tr.name,
                          "requests": len(tr.requests),
                          "duration_s": round(tr.duration_s, 3)}))
        return 0

    if args.cmd == "replay":
        tr = read_trace(args.trace)
        target = HttpTarget(args.target, model=args.model,
                                    seed=tr.seed, speed=args.speed)
        records = replay(tr, target, speed=args.speed)
        result = summarize(tr, records, speed=args.speed)
        failures = check_expect(tr.expect, result)
        result["expect_failures"] = failures
        print(json.dumps(result))
        if args.assert_expect and failures:
            for f in failures:
                print(f"expect FAIL: {f}", file=sys.stderr)
            return 1
        return 0

    if args.cmd == "record":
        ids = None
        if args.ids_file:
            with open(args.ids_file) as f:
                ids = [ln.strip() for ln in f if ln.strip()]
        tr = record_from_server(args.target, ids=ids,
                                        name=args.name)
        write_trace(tr, args.out)
        print(json.dumps({"written": args.out,
                          "requests": len(tr.requests),
                          "duration_s": round(tr.duration_s, 3)}))
        return 0

    if args.cmd == "describe":
        tr = read_trace(args.trace)
        by_tenant: dict[str, int] = {}
        groups: set[str] = set()
        for req in tr.requests:
            by_tenant[req.tenant or "-"] = \
                by_tenant.get(req.tenant or "-", 0) + 1
            if req.prefix_group:
                groups.add(req.prefix_group)
        print(json.dumps({
            "name": tr.name, "version": tr.version, "seed": tr.seed,
            "generator": tr.generator,
            "requests": len(tr.requests),
            "duration_s": round(tr.duration_s, 3),
            "prompt_tokens_total": sum(
                r.prompt_tokens for r in tr.requests),
            "max_new_total": sum(r.max_new for r in tr.requests),
            "abandoning": sum(1 for r in tr.requests
                              if r.abandon_at is not None),
            "prefix_groups": len(groups),
            "by_tenant": by_tenant,
            "expect": tr.expect,
        }))
        return 0

    return 2  # unreachable


if __name__ == "__main__":
    sys.exit(main())
