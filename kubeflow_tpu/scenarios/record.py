"""Record a live run into a replayable trace.

The serving app already keeps a bounded `TimelineStore` of per-request
`RequestTimeline`s (ISSUE 6) and serves them at
`/v1/requests/{id}/timeline` (ids enumerable at `/v1/requests/
timelines`). A timeline carries everything a faithful replay needs —
the enqueue stamp (arrival), tenant, prompt length, the max_new ask,
and whether the request finished — so ANY live run can be captured
after the fact: no recording flag, no second code path on the hot
side.

Offsets are re-based to the earliest enqueue in the capture, so a
recorded trace always starts at 0. A timeline that never reached
`finish` records as an abandoned arrival (abandon_at = its last
observed activity): replaying the capture reproduces the hang-up, not
an idealized patient client.

Prefix-group structure is NOT recoverable from timelines (the radix
tree sees token ids; the timeline, by design, stores none), so
recorded traces have empty groups — `meta.recorded_from` says so.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Iterable

from kubeflow_tpu.scenarios.trace import Trace, TraceRequest


def trace_from_timeline_payloads(
        payloads: Iterable[dict[str, Any]], *, name: str = "recorded",
        expect: dict | None = None,
        meta: dict | None = None) -> Trace:
    """Build a trace from `/v1/requests/{id}/timeline` response
    bodies. Payloads missing the recorder fields (`enqueue_monotonic_s`
    etc. — pre-extension servers) are rejected by name, not guessed
    around."""
    rows = []
    for p in payloads:
        missing = [k for k in ("request_id", "enqueue_monotonic_s",
                               "prompt_tokens", "max_new") if
                   p.get(k) in (None, "") and p.get(k) != 0]
        if missing:
            raise ValueError(
                f"timeline {p.get('request_id')!r} lacks recorder "
                f"fields {missing} — server predates the scenario "
                "recorder?")
        if p["prompt_tokens"] < 1 or p["max_new"] < 1:
            # warmup probes and degenerate asks are not replayable
            # arrivals; skip rather than invent lengths
            continue
        rows.append(p)
    if not rows:
        raise ValueError("no replayable timelines in the capture")
    t0 = min(p["enqueue_monotonic_s"] for p in rows)
    reqs = []
    for p in rows:
        at = p["enqueue_monotonic_s"] - t0
        abandon_at = None
        if not p.get("done"):
            # last observed activity relative to trace start; a
            # timeline with no tokens/events abandons at arrival
            last = max([p["enqueue_monotonic_s"]]
                       + [p["enqueue_monotonic_s"] + t
                          for t in p.get("token_times", [])]
                       + [p["enqueue_monotonic_s"] + e["t"]
                          for e in p.get("events", [])])
            abandon_at = last - t0
        reqs.append(TraceRequest(
            id=p["request_id"], at=at,
            prompt_tokens=int(p["prompt_tokens"]),
            max_new=int(p["max_new"]),
            tenant=p.get("tenant", ""),
            abandon_at=abandon_at))
    return Trace(name=name, requests=reqs, seed=0,
                 generator="recorded",
                 expect=expect or {"client_failures": {"max": 0}},
                 meta=dict(meta or {}, recorded_from="timeline_store",
                           prefix_groups_recovered=False))


def trace_from_store(store, *, name: str = "recorded",
                     expect: dict | None = None,
                     meta: dict | None = None) -> Trace:
    """In-process capture straight off a `TimelineStore`."""
    return trace_from_timeline_payloads(
        (tl.to_dict() for tl in store.snapshot()),
        name=name, expect=expect, meta=meta)


def fetch_timelines(base_url: str, ids: Iterable[str] | None = None,
                    *, timeout: float = 10.0) -> list[dict[str, Any]]:
    """Pull timelines over HTTP. With ids=None, enumerate the server's
    store via `/v1/requests/timelines`. Evicted ids (bounded store)
    404 and are skipped — the capture is best-effort by design."""
    base = base_url.rstrip("/")
    if ids is None:
        with urllib.request.urlopen(f"{base}/v1/requests/timelines",
                                    timeout=timeout) as r:
            ids = json.loads(r.read())["requests"]
    out = []
    for rid in ids:
        try:
            with urllib.request.urlopen(
                    f"{base}/v1/requests/{rid}/timeline",
                    timeout=timeout) as r:
                out.append(json.loads(r.read()))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
            e.close()
    return out


def record_from_server(base_url: str, *,
                       ids: Iterable[str] | None = None,
                       name: str = "recorded",
                       expect: dict | None = None,
                       meta: dict | None = None) -> Trace:
    """One-call capture: enumerate (or take) request ids, fetch their
    timelines, and fold them into a trace."""
    payloads = fetch_timelines(base_url, ids)
    return trace_from_timeline_payloads(
        payloads, name=name, expect=expect,
        meta=dict(meta or {}, source_url=base_url))
