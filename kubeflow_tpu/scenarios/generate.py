"""Deterministic scenario generators: the shapes that break schedulers.

Every generator takes an explicit integer seed and returns a `Trace`;
the same (generator, seed, params) tuple produces a byte-identical
trace file on every machine, forever — no wall-clock anywhere. That is
what makes a scenario a shareable artifact: "`flash-crowd` seed 7"
names the exact same arrival sequence in a bug report, a CI gate, and
a bench run.

The catalog (NotebookOS motivates the bursty interactive shapes,
Podracer the sustained swarm floods):

- `diurnal`       — sinusoidal load waves (the 24h cycle compressed),
- `flash_crowd`   — a quiet baseline, then everyone arrives at once
                    for the SAME content (shared prefix group),
- `heavy_tail`    — lognormal/Pareto prompt lengths: the p99 prompt
                    is the one that wrecks batch occupancy,
- `agent_swarm`   — N agents each re-querying with a growing shared
                    prefix (radix-cache reuse structure),
- `abandon_retry` — impatient clients that hang up and retry, the
                    storm that doubles offered load exactly when the
                    system is slowest,
- `tenant_flood`  — the `--mode tenants` noisy-neighbor arrival shape
                    (sustained bulk flood + periodic interactive
                    probes) expressed as a scenario file.

Arrival processes are Poisson (exponential gaps) unless the shape
says otherwise; nonhomogeneous rates use thinning so the draw count
per unit time stays seed-stable under parameter tweaks.
"""

from __future__ import annotations

import math
import random
from typing import Any, Callable

from kubeflow_tpu.scenarios.trace import Trace, TraceRequest


def _clip(v: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, v))


def _poisson_arrivals(rand: random.Random, rate: float,
                      duration_s: float) -> list[float]:
    """Homogeneous Poisson arrival offsets in [0, duration_s)."""
    out, t = [], 0.0
    while True:
        t += rand.expovariate(rate)
        if t >= duration_s:
            return out
        out.append(t)


def _thinned_arrivals(rand: random.Random, rate_fn: Callable[[float], float],
                      max_rate: float, duration_s: float) -> list[float]:
    """Nonhomogeneous Poisson via thinning: draw at max_rate, keep
    each arrival with probability rate(t)/max_rate."""
    out, t = [], 0.0
    while True:
        t += rand.expovariate(max_rate)
        if t >= duration_s:
            return out
        if rand.random() * max_rate < rate_fn(t):
            out.append(t)


def gen_diurnal(seed: int, *, duration_s: float = 20.0,
                base_rps: float = 2.0, peak_rps: float = 8.0,
                waves: int = 2, prompt_tokens: int = 24,
                max_new: int = 16) -> Trace:
    """Sinusoidal waves between base and peak rps — the 24h cycle an
    autoscaler must ride without thrashing, compressed to seconds."""
    rand = random.Random(f"diurnal:{seed}")
    mid = (base_rps + peak_rps) / 2.0
    amp = (peak_rps - base_rps) / 2.0

    def rate(t: float) -> float:
        return mid + amp * math.sin(2 * math.pi * waves * t / duration_s)

    reqs = [
        TraceRequest(id=f"r-{i:06d}", at=at,
                     prompt_tokens=_clip(
                         round(rand.gauss(prompt_tokens,
                                          prompt_tokens / 4)),
                         4, 4 * prompt_tokens),
                     max_new=max_new)
        for i, at in enumerate(_thinned_arrivals(
            rand, rate, peak_rps, duration_s))
    ]
    return Trace(
        name=f"diurnal-s{seed}", requests=reqs, seed=seed,
        generator="diurnal",
        expect={"client_failures": {"max": 0},
                "completed_frac": {"min": 1.0}},
        meta={"duration_s": duration_s, "base_rps": base_rps,
              "peak_rps": peak_rps, "waves": waves})


def gen_flash_crowd(seed: int, *, duration_s: float = 12.0,
                    base_rps: float = 1.0, burst_at_frac: float = 0.4,
                    burst_len_s: float = 2.0, burst_rps: float = 15.0,
                    prompt_tokens: int = 24, prefix_tokens: int = 16,
                    max_new: int = 8) -> Trace:
    """Quiet baseline, then a burst window where arrivals spike an
    order of magnitude — and the crowd all wants the SAME thing, so
    burst requests share one prefix group (the radix cache either
    absorbs the stampede or every request re-prefills the same
    tokens)."""
    rand = random.Random(f"flash_crowd:{seed}")
    burst_t0 = burst_at_frac * duration_s
    base = _poisson_arrivals(rand, base_rps, duration_s)
    burst = [burst_t0 + t for t in
             _poisson_arrivals(rand, burst_rps, burst_len_s)]
    reqs = [TraceRequest(id=f"b-{i:06d}", at=at,
                         prompt_tokens=prompt_tokens, max_new=max_new)
            for i, at in enumerate(base)]
    reqs += [TraceRequest(id=f"c-{i:06d}", at=at,
                          prompt_tokens=prompt_tokens,
                          max_new=max_new,
                          prefix_group="crowd",
                          prefix_tokens=prefix_tokens)
             for i, at in enumerate(burst)]
    return Trace(
        name=f"flash-crowd-s{seed}", requests=reqs, seed=seed,
        generator="flash_crowd",
        expect={"client_failures": {"max": 0},
                "completed_frac": {"min": 1.0}},
        meta={"duration_s": duration_s, "base_rps": base_rps,
              "burst_t0_s": round(burst_t0, 6),
              "burst_len_s": burst_len_s, "burst_rps": burst_rps})


def gen_heavy_tail(seed: int, *, n: int = 60, rps: float = 4.0,
                   dist: str = "pareto", alpha: float = 1.2,
                   scale: float = 8.0, max_prompt: int = 96,
                   max_new: int = 8) -> Trace:
    """Heavy-tailed prompt lengths (Pareto or lognormal): most
    prompts are short, but the tail mass is where chunked prefill and
    batch-occupancy policies earn their keep."""
    if dist not in ("pareto", "lognormal"):
        raise ValueError(f"unknown dist {dist!r}")
    rand = random.Random(f"heavy_tail:{dist}:{seed}")
    arrivals = []
    t = 0.0
    for _ in range(n):
        t += rand.expovariate(rps)
        arrivals.append(t)
    reqs = []
    for i, at in enumerate(arrivals):
        if dist == "pareto":
            ln = scale * rand.paretovariate(alpha)
        else:
            ln = rand.lognormvariate(math.log(scale), 0.9)
        reqs.append(TraceRequest(
            id=f"r-{i:06d}", at=at,
            prompt_tokens=_clip(round(ln), 2, max_prompt),
            max_new=max_new))
    return Trace(
        name=f"heavy-tail-{dist}-s{seed}", requests=reqs, seed=seed,
        generator="heavy_tail",
        expect={"client_failures": {"max": 0},
                "completed_frac": {"min": 1.0}},
        meta={"n": n, "rps": rps, "dist": dist, "alpha": alpha,
              "scale": scale, "max_prompt": max_prompt})


def gen_agent_swarm(seed: int, *, agents: int = 8,
                    steps_per_agent: int = 6, think_s: float = 0.8,
                    prefix_tokens: int = 24, step_tokens: int = 6,
                    max_new: int = 8, stagger_s: float = 0.3) -> Trace:
    """N agents, each looping generate -> think -> generate with a
    growing conversation: step k of agent a shares the agent's prefix
    group with prefix length prefix_tokens (the system prompt) and a
    prompt that grows by step_tokens per turn. Prefix-skew is the
    point — a router that ignores it re-prefills every turn."""
    rand = random.Random(f"agent_swarm:{seed}")
    reqs = []
    for a in range(agents):
        t = a * stagger_s * rand.uniform(0.5, 1.5)
        for k in range(steps_per_agent):
            reqs.append(TraceRequest(
                id=f"a{a:03d}-k{k:02d}", at=t,
                prompt_tokens=prefix_tokens + (k + 1) * step_tokens,
                max_new=max_new,
                tenant="swarm", priority="batch",
                prefix_group=f"agent-{a}",
                prefix_tokens=prefix_tokens))
            t += think_s * rand.uniform(0.6, 1.4)
    return Trace(
        name=f"agent-swarm-s{seed}", requests=reqs, seed=seed,
        generator="agent_swarm",
        expect={"client_failures": {"max": 0},
                "completed_frac": {"min": 1.0}},
        meta={"agents": agents, "steps_per_agent": steps_per_agent,
              "prefix_tokens": prefix_tokens,
              "step_tokens": step_tokens})


def gen_abandon_retry(seed: int, *, n: int = 24, rps: float = 3.0,
                      abandon_frac: float = 0.4,
                      patience_s: float = 0.06,
                      retry_delay_s: float = 0.5,
                      max_retries: int = 2,
                      prompt_tokens: int = 20,
                      max_new: int = 24,
                      abandon_max_new: int = 96) -> Trace:
    """Impatient clients: a fraction abandons after `patience_s` and
    retries the SAME ask (same prefix group) a moment later —
    retries arrive exactly when the system is already slow, and an
    engine that doesn't cancel abandoned work decodes into dead
    sockets while live clients queue.

    Like every shape here, time is compressed: abandoning attempts
    ask for `abandon_max_new` tokens against a `patience_s` far below
    any possible completion time, so EVERY scheduled hang-up fires
    regardless of server speed and the expect block can pin the exact
    abandoned count (a patience the server can outrun would make the
    count a race)."""
    if not (0 <= abandon_frac <= 1):
        raise ValueError("abandon_frac must be in [0, 1]")
    rand = random.Random(f"abandon_retry:{seed}")
    reqs = []
    t = 0.0
    for i in range(n):
        t += rand.expovariate(rps)
        impatient = rand.random() < abandon_frac
        retries = rand.randint(1, max_retries) if impatient else 0
        at = t
        for attempt in range(retries + 1):
            last = attempt == retries
            abandon_at = None if last else \
                at + patience_s * rand.uniform(0.8, 1.2)
            reqs.append(TraceRequest(
                id=f"r-{i:06d}-t{attempt}", at=at,
                prompt_tokens=prompt_tokens,
                max_new=max_new if last else abandon_max_new,
                prefix_group=f"ask-{i}",
                prefix_tokens=prompt_tokens // 2,
                abandon_at=abandon_at))
            if not last:
                at = abandon_at + retry_delay_s * rand.uniform(0.8, 1.2)
    n_abandon = sum(1 for r in reqs if r.abandon_at is not None)
    return Trace(
        name=f"abandon-retry-s{seed}", requests=reqs, seed=seed,
        generator="abandon_retry",
        expect={"client_failures": {"max": 0},
                "abandoned": {"min": n_abandon, "max": n_abandon},
                "completed": {"min": len(reqs) - n_abandon}},
        meta={"n": n, "rps": rps, "abandon_frac": abandon_frac,
              "patience_s": patience_s,
              "retry_delay_s": retry_delay_s})


def gen_tenant_flood(seed: int, *, duration_s: float = 8.0,
                     bulk_rps: float = 16.0, bulk_prompt: int = 12,
                     bulk_max_new: int = 96,
                     live_period_s: float = 0.5,
                     live_prompt: int = 4,
                     live_max_new: int = 8) -> Trace:
    """The `--mode tenants` noisy-neighbor arrival shape as a
    scenario: a batch-class bulk flood (Poisson, long generations)
    with an interactive probe streaming through the backlog at a
    fixed cadence. This is the loadtest's tenants flood expressed as
    data instead of harness code.

    Defaults are sized to genuinely saturate the loadtest's tiny CPU
    engine (offered decode work slightly above capacity), so TTFT is
    set by queue structure — which a faithful record/replay
    round-trip reproduces — rather than by scheduler noise. That is
    what makes this the fidelity arm's reference shape."""
    rand = random.Random(f"tenant_flood:{seed}")
    reqs = [TraceRequest(
        id=f"bulk-{i:06d}", at=at,
        prompt_tokens=_clip(round(rand.gauss(bulk_prompt,
                                             bulk_prompt / 4)),
                            2, 4 * bulk_prompt),
        max_new=bulk_max_new, tenant="bulk", priority="batch")
        for i, at in enumerate(_poisson_arrivals(
            rand, bulk_rps, duration_s))]
    n_live = int(duration_s / live_period_s)
    # first probe after one period: the flood needs a backlog to be
    # noisy about
    reqs += [TraceRequest(
        id=f"live-{i:06d}", at=(i + 1) * live_period_s,
        prompt_tokens=live_prompt, max_new=live_max_new,
        tenant="live", priority="interactive")
        for i in range(n_live - 1)]
    return Trace(
        name=f"tenant-flood-s{seed}", requests=reqs, seed=seed,
        generator="tenant_flood",
        expect={"client_failures": {"max": 0},
                "completed_frac": {"min": 1.0}},
        meta={"duration_s": duration_s, "bulk_rps": bulk_rps,
              "bulk_max_new": bulk_max_new,
              "live_period_s": live_period_s})


GENERATORS: dict[str, Callable[..., Trace]] = {
    "diurnal": gen_diurnal,
    "flash_crowd": gen_flash_crowd,
    "heavy_tail": gen_heavy_tail,
    "agent_swarm": gen_agent_swarm,
    "abandon_retry": gen_abandon_retry,
    "tenant_flood": gen_tenant_flood,
}


def generate(shape: str, seed: int, **params: Any) -> Trace:
    """Look up a generator by name (`-` and `_` interchangeable) and
    run it. Unknown shapes and unknown params fail loudly — a typo'd
    scenario must not silently become the default one."""
    key = shape.replace("-", "_")
    fn = GENERATORS.get(key)
    if fn is None:
        raise ValueError(
            f"unknown scenario shape {shape!r}; known: "
            f"{sorted(GENERATORS)}")
    return fn(seed, **params)
