"""Open-loop trace replay: fire arrivals at recorded offsets, judge
the outcome against the trace's `expect` block.

Open-loop is the property that matters: a closed-loop harness (next
request waits for the last response) silently sheds load exactly when
the system degrades — the worst moment to look away. Here every
arrival fires at `at / speed` seconds after start whether or not the
target is keeping up, so queue meltdown shows up as TTFT, not as a
politely thinned workload (the coordinated-omission trap).

The engine is dependency-injected end to end: `clock`, `sleep`, and
the per-request `submit` callable are parameters, so tests drive a
fake clock and assert exact arrival fidelity, while the real
`HttpTarget` drives any serving endpoint (single replica or the fleet
router — same generate surface) with streamed SSE requests, measuring
TTFT at the first token frame and hanging up at `abandon_at` like the
impatient client the trace describes.

Prompt token ids are derived deterministically from (trace seed,
prefix_group, request id): requests in a group share their first
`prefix_tokens` ids, reproducing the radix-reuse structure without
shipping content.
"""

from __future__ import annotations

import concurrent.futures
import http.client
import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable

from kubeflow_tpu.scenarios.trace import Trace, TraceRequest

# Derived prompt token ids stay in a small band well inside every
# tiny-model vocab (and matching the loadtests' idiom) so one trace
# replays against any family.
_VOCAB_BAND = 480
_TOKEN_BASE = 5


def prompt_ids_for(req: TraceRequest, seed: int) -> list[int]:
    """Deterministic prompt for a trace request. Same group -> same
    first `prefix_tokens` ids; the remainder is unique per request id.
    Uses a hand-rolled LCG over a stable string hash (not `random`) so
    the mapping is frozen independent of stdlib implementation."""
    def stream(key: str, n: int) -> list[int]:
        # FNV-1a over the key seeds a 64-bit LCG
        h = 0xcbf29ce484222325
        for b in key.encode():
            h = ((h ^ b) * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
        out = []
        for _ in range(n):
            h = (h * 6364136223846793005 + 1442695040888963407) \
                & 0xFFFFFFFFFFFFFFFF
            out.append(_TOKEN_BASE + (h >> 33) % _VOCAB_BAND)
        return out

    shared = stream(f"{seed}:{req.prefix_group}", req.prefix_tokens) \
        if req.prefix_group else []
    rest = stream(f"{seed}:{req.prefix_group}:{req.id}",
                  req.prompt_tokens - len(shared))
    return shared + rest


def replay(trace: Trace,
           submit: Callable[[TraceRequest, float], dict[str, Any]], *,
           speed: float = 1.0,
           clock: Callable[[], float] = time.monotonic,
           sleep: Callable[[float], None] = time.sleep,
           max_workers: int = 64) -> list[dict[str, Any]]:
    """Drive every trace request through `submit` at its arrival
    offset. `submit(req, t0)` runs on a worker thread and returns the
    per-request record; the engine stamps scheduling fidelity on top
    (`scheduled_at`, `dispatched_at` — both in trace-time seconds,
    i.e. already multiplied back by speed)."""
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    records: list[dict[str, Any]] = []
    lock = threading.Lock()

    def worker(req: TraceRequest, t0: float) -> None:
        dispatched = (clock() - t0) * speed
        try:
            rec = submit(req, t0)
        except Exception as e:  # a submit that raises is a failure,
            rec = {"ok": False,  # not a harness crash
                   "abandoned": False, "tokens": 0, "ttft_s": None,
                   "error": f"{type(e).__name__}: {e}"}
        rec.update(id=req.id, scheduled_at=req.at,
                   dispatched_at=round(dispatched, 6))
        with lock:
            records.append(rec)

    t0 = clock()
    with concurrent.futures.ThreadPoolExecutor(max_workers) as ex:
        futs = []
        for req in trace.requests:  # sorted by (at, id)
            target = req.at / speed
            while True:
                delta = target - (clock() - t0)
                if delta <= 0:
                    break
                sleep(delta)
            futs.append(ex.submit(worker, req, t0))
        for f in futs:
            f.result()  # surface harness bugs, not request failures
    records.sort(key=lambda r: (r["scheduled_at"], r["id"]))
    return records


class HttpTarget:
    """Submit callable for a live serving endpoint (replica or fleet
    router — the generate surface is identical). Streams SSE so TTFT
    is measured at the first token frame on the wire, and closes the
    connection at `abandon_at` to exercise the cancellation path."""

    def __init__(self, base_url: str, *, model: str = "tiny",
                 seed: int = 0, speed: float = 1.0,
                 timeout_s: float = 180.0,
                 clock: Callable[[], float] = time.monotonic):
        self.base = base_url.rstrip("/")
        self.model = model
        self.seed = seed
        self.speed = speed
        self.timeout_s = timeout_s
        self.clock = clock

    def __call__(self, req: TraceRequest, t0: float) -> dict[str, Any]:
        body = json.dumps({
            "tokens": [prompt_ids_for(req, self.seed)],
            "max_new": req.max_new, "stream": True}).encode()
        headers = {"Content-Type": "application/json",
                   "X-Request-Id": req.id}
        if req.tenant:
            headers["X-Tenant"] = req.tenant
        hreq = urllib.request.Request(
            f"{self.base}/v1/models/{self.model}:generate",
            data=body, headers=headers)
        # abandon deadline in REPLAY time (trace offsets scale by speed)
        deadline = (t0 + req.abandon_at / self.speed
                    if req.abandon_at is not None else None)
        sent = self.clock()
        ttft = None
        tokens = 0
        timer = None

        def hung_up() -> bool:
            return deadline is not None and self.clock() >= deadline

        try:
            with urllib.request.urlopen(
                    hreq, timeout=self.timeout_s) as r:
                if deadline is not None:
                    # the hang-up must fire even while BLOCKED waiting
                    # for the next frame (a queued request emits
                    # nothing to react to): a timer closes the
                    # response out from under the reader, which then
                    # raises and is booked abandoned below
                    timer = threading.Timer(
                        max(0.0, deadline - self.clock()), r.close)
                    timer.daemon = True
                    timer.start()
                for line in r:
                    if hung_up():
                        return {"ok": True, "abandoned": True,
                                "tokens": tokens, "ttft_s": ttft,
                                "wall_s": round(
                                    self.clock() - sent, 6)}
                    if not line.startswith(b"data: "):
                        continue
                    ev = json.loads(line[len(b"data: "):])
                    if ev.get("error"):
                        return {"ok": False, "abandoned": False,
                                "tokens": tokens, "ttft_s": ttft,
                                "error": str(ev["error"])}
                    if ev.get("done"):
                        break
                    got = ev.get("tokens")
                    if got:
                        if ttft is None:
                            ttft = self.clock() - sent
                        tokens += len(got[0])
        except (urllib.error.URLError, OSError, ValueError,
                AttributeError, http.client.HTTPException) as e:
            # AttributeError is http.client's artifact of close() from
            # the abandon timer landing mid-read (self.fp becomes
            # None); it IS the hang-up, not a harness bug
            if hung_up():
                return {"ok": True, "abandoned": True,
                        "tokens": tokens, "ttft_s": ttft,
                        "wall_s": round(self.clock() - sent, 6)}
            return {"ok": False, "abandoned": False, "tokens": tokens,
                    "ttft_s": ttft, "error": f"{type(e).__name__}: {e}"}
        finally:
            if timer is not None:
                timer.cancel()
        if deadline is not None and self.clock() >= deadline:
            # finished at/after the hang-up instant: the trace said
            # this client never saw the end — book it abandoned
            return {"ok": True, "abandoned": True, "tokens": tokens,
                    "ttft_s": ttft,
                    "wall_s": round(self.clock() - sent, 6)}
        return {"ok": True, "abandoned": False, "tokens": tokens,
                "ttft_s": ttft, "wall_s": round(self.clock() - sent, 6)}


def percentile(xs: list[float], q: float) -> float | None:
    if not xs:
        return None
    s = sorted(xs)
    return s[min(len(s) - 1, int(q * len(s)))]


def summarize(trace: Trace, records: list[dict[str, Any]], *,
              speed: float = 1.0) -> dict[str, Any]:
    """Fold per-request records into the result dict the `expect`
    block is evaluated against. Keys here ARE the expect vocabulary —
    add a key, and scenarios can gate on it."""
    completed = [r for r in records if r["ok"] and not r["abandoned"]]
    abandoned = [r for r in records if r["abandoned"]]
    failed = [r for r in records if not r["ok"]]
    ttfts = sorted(r["ttft_s"] for r in records
                   if r.get("ttft_s") is not None)
    skews = sorted(r["dispatched_at"] - r["scheduled_at"]
                   for r in records)
    offered = len(trace.requests)
    out = {
        "scenario": trace.name,
        "seed": trace.seed,
        "speed": speed,
        "offered": offered,
        "completed": len(completed),
        "completed_frac": round(len(completed) / offered, 4)
        if offered else 0.0,
        "abandoned": len(abandoned),
        "client_failures": len(failed),
        "tokens_out": sum(r["tokens"] for r in records),
        "ttft_p50_s": (round(percentile(ttfts, 0.50), 6)
                       if ttfts else None),
        "ttft_p95_s": (round(percentile(ttfts, 0.95), 6)
                       if ttfts else None),
        "ttft_max_s": round(ttfts[-1], 6) if ttfts else None,
        "arrival_skew_p95_s": (round(percentile(skews, 0.95), 6)
                               if skews else None),
        "duration_s": round(trace.duration_s / speed, 6),
    }
    if failed:
        out["first_error"] = failed[0].get("error")
    return out


def check_expect(expect: dict[str, dict[str, float]],
                 result: dict[str, Any]) -> list[str]:
    """Evaluate a trace's declarative expect block against a replay
    result. Returns human-readable violations (empty == pass). A bound
    on a key the result lacks — or that is None (e.g. p95 of zero
    observations) — is itself a violation: a scenario asserting on a
    metric that never materialized must fail, not vacuously pass."""
    failures = []
    for key, bounds in expect.items():
        v = result.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            failures.append(
                f"expect[{key}]: result has no numeric value "
                f"(got {v!r})")
            continue
        lo, hi = bounds.get("min"), bounds.get("max")
        if lo is not None and v < lo:
            failures.append(f"expect[{key}]: {v} < min {lo}")
        if hi is not None and v > hi:
            failures.append(f"expect[{key}]: {v} > max {hi}")
    return failures


def assert_expect(trace: Trace, result: dict[str, Any]) -> None:
    failures = check_expect(trace.expect, result)
    if failures:
        raise AssertionError(
            f"scenario {trace.name!r} violated its expect block: "
            + "; ".join(failures))
