"""Sharded training: FSDP/TP train step over a (data, fsdp, tensor) mesh.

The TPU-idiomatic training recipe (scaling-book style):
  1. pick a Mesh (kubeflow_tpu.parallel.mesh),
  2. resolve logical param axes → NamedShardings (parallel.sharding),
  3. jit the step with in/out shardings; XLA inserts the all-gathers /
     reduce-scatters over ICI.
No hand-written collectives in the DP/FSDP/TP path — that is XLA's job.
Ring attention / EP (explicit collectives via shard_map) live in
kubeflow_tpu.parallel and compose with this trainer.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu import obs
from kubeflow_tpu.parallel import mesh as mesh_lib
from kubeflow_tpu.parallel import sharding as sharding_lib
from kubeflow_tpu.parallel.sharding import ShardingRules

Params = Any


def estimate_step_flops(n_params: int, tokens: int) -> float:
    """Model FLOPs for one train step: the standard 6·N·T estimate
    (2·N·T forward + 4·N·T backward) over all processed tokens. This is
    MODEL flops — the numerator of MFU — not hardware flops: attention
    quadratic terms and rematerialization are deliberately excluded, so
    MFU stays comparable across implementations (the scaling-book
    convention the paper's goodput accounting uses)."""
    return 6.0 * float(n_params) * float(tokens)


def _masked_mean(
    nll: jnp.ndarray,                 # [b, s] per-position losses
    mask: jnp.ndarray | None,         # [b, s] float/bool, 0 = ignore
) -> jnp.ndarray:
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def cross_entropy_loss(
    logits: jnp.ndarray,   # [b, s, vocab] fp32
    targets: jnp.ndarray,  # [b, s] int32
    mask: jnp.ndarray | None = None,  # [b, s] float/bool, 0 = ignore
) -> jnp.ndarray:
    """Mean next-token cross entropy over valid positions."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return _masked_mean(logz - gold, mask)


def chunked_cross_entropy_from_hidden(
    hidden: jnp.ndarray,    # [b, s, D] final (normed) hidden states
    head: jnp.ndarray,      # [D, vocab] unembedding matrix
    targets: jnp.ndarray,   # [b, s] int32
    mask: jnp.ndarray | None = None,
    *,
    num_chunks: int = 8,
) -> jnp.ndarray:
    """CE without materializing the full [b, s, vocab] fp32 logits.

    The logit tensor is the single largest activation of a big-vocab
    training step (batch 8 x seq 2048 x 32k vocab = 2 GB fp32, doubled
    by its cotangent). Flash-attention's trick applies to the softmax
    over vocab too: scan over vocab CHUNKS, keep the online
    (max, sumexp, gold-logit) running stats, and `jax.checkpoint` the
    chunk body so the backward pass recomputes each chunk's logits
    instead of storing them. Peak logit memory drops num_chunks-fold;
    HBM traffic for the step's biggest tensor drops with it.

    Numerics match `cross_entropy_loss(hidden @ head, ...)` to fp32
    rounding (same online-softmax algebra as ops/pallas/flash_attention).
    """
    b, s, d = hidden.shape
    vocab = head.shape[1]
    # Largest divisor of vocab <= requested: never silently degrade to
    # one full-vocab chunk (that would materialize exactly the logits
    # this function exists to avoid).
    requested = num_chunks
    num_chunks = max(1, min(num_chunks, vocab))
    while vocab % num_chunks:
        num_chunks -= 1
    if num_chunks == 1 and requested > 1 and vocab > 4096:
        logging.getLogger(__name__).warning(
            "chunked CE running UNCHUNKED: vocab %d shares no divisor "
            "<= the requested chunk count %d — full [b, s, vocab] "
            "logits will materialize", vocab, requested)
    chunk = vocab // num_chunks
    hidden = hidden.astype(jnp.float32)
    offsets = (jnp.arange(num_chunks, dtype=jnp.int32) * chunk)

    @jax.checkpoint
    def body(carry, off):
        m, acc, gold = carry
        # Slice the head in its NATIVE dtype and cast per chunk: an
        # fp32 copy of the whole [D, vocab] head as a scan operand
        # would itself cost ~half the memory the chunking saves.
        head_c = jax.lax.dynamic_slice(head, (0, off), (d, chunk))
        logits_c = hidden @ head_c.astype(jnp.float32)  # [b, s, chunk]
        m_c = jnp.max(logits_c, axis=-1)
        new_m = jnp.maximum(m, m_c)
        acc = (acc * jnp.exp(m - new_m)
               + jnp.sum(jnp.exp(logits_c - new_m[..., None]), axis=-1))
        local = targets - off
        in_chunk = (local >= 0) & (local < chunk)
        picked = jnp.take_along_axis(
            logits_c, jnp.clip(local, 0, chunk - 1)[..., None], axis=-1
        )[..., 0]
        gold = gold + jnp.where(in_chunk, picked, 0.0)
        return (new_m, acc, gold), None

    init = (
        jnp.full((b, s), -jnp.inf, jnp.float32),
        jnp.zeros((b, s), jnp.float32),
        jnp.zeros((b, s), jnp.float32),
    )
    (m, acc, gold), _ = jax.lax.scan(body, init, offsets)
    return _masked_mean((m + jnp.log(acc)) - gold, mask)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    # Gradient accumulation: split each step's batch into this many
    # microbatches and average their grads (mask-weighted, fp32
    # accumulator) before ONE optimizer update — the peak-activation
    # memory of a batch/grad_accum step at the optimizer behavior of
    # the full batch. 1 = off.
    grad_accum: int = 1
    # adamw (2x-params moments) or adafactor (factored second moment —
    # the classic TPU memory saver: 8B-model Adam state is 64 GB fp32,
    # Adafactor's is ~params/row+col factors).
    optimizer: str = "adamw"
    # ZeRO-style optimizer partitioning: moments that mirror a param
    # additionally shard over the data axis (parallel.sharding.
    # zero_extend_sharding), so each data-parallel replica holds ~1/N
    # of the optimizer state and XLA lowers the update to
    # reduce-scatter(grads) + sharded update + all-gather(params)
    # instead of N redundant full updates. Exact no-op on data=1
    # meshes. Off reproduces plain mirrored (replicated-over-data)
    # moments — the bench A/B baseline.
    zero_optimizer: bool = True


class TrainState:
    """Minimal pytree train state (params, opt_state, step)."""

    def __init__(self, params, opt_state, step):
        self.params = params
        self.opt_state = opt_state
        self.step = step

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten
)


def make_optimizer(
    tc: TrainConfig,
    freeze_labels: Params | None = None,
) -> optax.GradientTransformation:
    """AdamW with warmup-cosine. `freeze_labels` (a params-shaped tree
    of "train"/"freeze") carves the tree into a trained group and a
    frozen one whose updates are zero AND whose optimizer state is
    empty — for LoRA that empty state is the point: adapter moments
    are ~1000x smaller than full-model moments."""
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=tc.learning_rate,
        warmup_steps=tc.warmup_steps,
        decay_steps=max(tc.total_steps, tc.warmup_steps + 1),
        end_value=tc.learning_rate * 0.1,
    )
    if tc.optimizer == "adamw":
        inner = optax.adamw(schedule, b1=tc.b1, b2=tc.b2,
                            weight_decay=tc.weight_decay)
    elif tc.optimizer == "adafactor":
        # factored second moment: the non-mirroring factor leaves fall
        # through _opt_state_shardings' path+shape match and replicate,
        # which is exactly right — they are O(rows+cols), not O(params)
        inner = optax.adafactor(
            learning_rate=schedule, weight_decay_rate=tc.weight_decay
            or None)
    else:
        raise ValueError(f"unknown optimizer {tc.optimizer!r} "
                         "(adamw | adafactor)")
    opt = optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        inner,
    )
    if freeze_labels is None:
        return opt
    return optax.multi_transform(
        {"train": opt, "freeze": optax.set_to_zero()}, freeze_labels)


class Trainer:
    """Builds sharded init/step functions for a model on a mesh.

    `apply_fn(params, tokens) -> logits`; `init_fn(rng) -> params`;
    `logical_axes`: pytree of logical axis tuples matching params.
    """

    def __init__(
        self,
        *,
        mesh: Mesh,
        apply_fn: Callable[..., jnp.ndarray],
        init_fn: Callable[[jax.Array], Params],
        logical_axes: Params,
        rules: ShardingRules = sharding_lib.LLAMA_RULES,
        train_config: TrainConfig = TrainConfig(),
        loss_fn: Callable[..., jnp.ndarray] | None = None,
        freeze_labels: Params | None = None,
        tracer=None,
        registry=None,
    ):
        """`loss_fn(params, tokens, targets, mask) -> scalar` overrides
        the default apply_fn→cross-entropy pipeline — e.g.
        `chunked_cross_entropy_from_hidden` over `llama.hidden`, which
        skips materializing the [b, s, vocab] logits entirely.
        `freeze_labels` (params-shaped "train"/"freeze" tree) freezes a
        subtree with no optimizer state (see make_optimizer)."""
        self.mesh = mesh
        self.apply_fn = apply_fn
        self.init_fn = init_fn
        self.rules = rules
        self.tc = train_config
        self.loss_fn = loss_fn
        self.optimizer = make_optimizer(train_config, freeze_labels)

        self.param_shardings = sharding_lib.shard_pytree_specs(
            rules, logical_axes, mesh
        )
        # Optimizer state shards like the params it mirrors; scalars replicate.
        params_shapes = jax.eval_shape(init_fn, jax.ShapeDtypeStruct((2,), jnp.uint32))
        opt_shapes = jax.eval_shape(self.optimizer.init, params_shapes)
        self.opt_shardings = _opt_state_shardings(
            opt_shapes, params_shapes, self.param_shardings, mesh
        )
        if train_config.zero_optimizer:
            self.opt_shardings = jax.tree_util.tree_map(
                lambda leaf, sh: sharding_lib.zero_extend_sharding(
                    sh, getattr(leaf, "shape", ())),
                opt_shapes, self.opt_shardings)
        self.state_shardings = TrainState(
            self.param_shardings, self.opt_shardings, NamedSharding(mesh, P())
        )
        # Abstract state tree (ShapeDtypeStructs), the public handle for
        # checkpoint restore targets — keeps callers off _init.
        self.state_shapes = TrainState(
            params_shapes, opt_shapes,
            jax.ShapeDtypeStruct((), jnp.int32),
        )
        # Batch splits over every data-parallel axis the mesh actually
        # has: the hybrid multi-slice mesh adds an outer "dcn" axis
        # (cross-slice pure DP — one grad all-reduce over DCN per step).
        batch_axes = tuple(
            a for a in ("dcn", "data", "fsdp") if a in mesh.axis_names
        )
        self.batch_sharding = NamedSharding(mesh, P(batch_axes, None))

        self._jit_init = jax.jit(self._init, out_shardings=self.state_shardings)
        # Warm-start builder (init_from_params): cached so sweeps that
        # fine-tune from many checkpoints compile it once.
        self._jit_build_state = jax.jit(
            self._build_state,
            in_shardings=(self.param_shardings,),
            out_shardings=self.state_shardings,
        )
        self._jit_step = jax.jit(
            self._step,
            in_shardings=(self.state_shardings, self.batch_sharding,
                          self.batch_sharding, self.batch_sharding),
            out_shardings=(self.state_shardings, NamedSharding(mesh, P())),
            donate_argnums=(0,),
        )
        # Obs bridge (spans + /metrics histograms). The Trainer has no
        # natural registry owner, so the process defaults apply unless a
        # caller injects shared ones; get_or_create keeps many Trainers
        # in one process (sweeps, tests) on the same series.
        self.tracer = tracer if tracer is not None else obs.DEFAULT_TRACER
        reg = registry if registry is not None else obs.default_registry()
        self.step_seconds = obs.get_or_create_histogram(
            reg, "train_step_seconds",
            "train step wall time: dispatch only once compiled (jit is "
            "async — use StepTimer(ready=...) for device step time); the "
            "first call blocks on trace+compile")
        self.compile_seconds = obs.get_or_create_histogram(
            reg, "train_compile_seconds",
            "first-step trace+compile+execute wall time (the north-star "
            "pod-to-first-compile component this process controls)",
            buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                     120.0, 300.0, 600.0))
        self._stepped = False
        # Step-anatomy plane (ISSUE 8): the SAME PhaseProfiler the
        # serving batcher uses, with the training anatomy — `step`
        # (the jit call) and `host_gap` (wall between consecutive
        # steps: input pipeline, checkpointing, logging). Goodput for
        # a trainer is step-time over (step + host_gap).
        self.profiler = obs.PhaseProfiler(phases=obs.TRAIN_PHASES)
        self.phase_seconds = obs.get_or_create_histogram(
            reg, "train_step_phase_seconds",
            "Wall time per training phase: step (jit dispatch; the "
            "first call blocks through compile) and host_gap (time "
            "between consecutive steps)")
        for _p in obs.TRAIN_PHASES:
            self.phase_seconds.seed(phase=_p)

        def _on_phase(phase, seconds, tokens):
            if seconds is not None:
                self.phase_seconds.observe(seconds, phase=phase)

        self.profiler.on_phase = _on_phase
        # Compile-watch over the jitted step: a batch/seq shape change
        # mid-run is a retrace the owner should know about (it stalls
        # every replica for the compile) — counted per fn, with a
        # `recompile` span naming the offending signature.
        self.recompiles = reg.get("train_recompiles_total")
        if self.recompiles is None:
            from kubeflow_tpu.controlplane.metrics import Counter

            self.recompiles = Counter(
                "train_recompiles_total",
                "Retraces of the jitted train step (novel abstract "
                "batch shape past the first compile)", reg)
        self._compile_watch = obs.CompileWatch(
            tracer=self.tracer,
            on_recompile=lambda fn, sig: self.recompiles.inc(fn=fn))
        self._jit_step = self._compile_watch.watch(
            self._jit_step, "train_step")
        self.recompiles.inc(0, fn="train_step")
        self._last_step_end: float | None = None

    def _build_state(self, params: Params) -> TrainState:
        return TrainState(params, self.optimizer.init(params),
                          jnp.zeros((), jnp.int32))

    def _init(self, rng: jax.Array) -> TrainState:
        return self._build_state(self.init_fn(rng))

    def _step(self, state: TrainState, tokens, targets, mask):
        def loss_fn(params, toks, tgts, m):
            if self.loss_fn is not None:
                return self.loss_fn(params, toks, tgts, m)
            logits = self.apply_fn(params, toks)
            return cross_entropy_loss(logits, tgts, m)

        acc = self.tc.grad_accum
        if acc <= 1:
            loss, grads = jax.value_and_grad(loss_fn)(
                state.params, tokens, targets, mask)
        else:
            # lax.scan over microbatches: ONE compiled micro-step,
            # peak activations 1/acc of the full batch. Each micro
            # loss is a masked MEAN, so grads/losses are re-weighted
            # by the micro's mask mass — mathematically identical to
            # the full-batch step (summation order aside), which the
            # parity test pins to tight tolerance.
            b = tokens.shape[0]
            mb = b // acc
            split = lambda a: a.reshape(acc, mb, *a.shape[1:])  # noqa: E731
            xs = (split(tokens), split(targets), split(mask))
            g0 = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)

            def micro(carry, x):
                gsum, lsum, wsum = carry
                toks, tgts, m = x
                l_, g_ = jax.value_and_grad(loss_fn)(
                    state.params, toks, tgts, m)
                w = jnp.sum(m.astype(jnp.float32))
                gsum = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32) * w, gsum, g_)
                return (gsum, lsum + l_.astype(jnp.float32) * w,
                        wsum + w), None

            (gsum, lsum, wsum), _ = jax.lax.scan(
                micro, (g0, jnp.zeros((), jnp.float32),
                        jnp.zeros((), jnp.float32)), xs)
            denom = jnp.maximum(wsum, 1.0)
            grads = jax.tree.map(
                lambda g, p: (g / denom).astype(p.dtype), gsum,
                state.params)
            loss = lsum / denom
        updates, opt_state = self.optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    def init(self, rng: jax.Array) -> TrainState:
        with mesh_lib.set_mesh(self.mesh):
            return self._jit_init(rng)

    def init_from_params(self, params: Params) -> TrainState:
        """Warm-start: fresh optimizer state around EXISTING params
        (fine-tuning from a checkpoint). Params are a jit argument, not
        a closure constant — closing over an 8B tree would bake it into
        the executable."""
        with mesh_lib.set_mesh(self.mesh):
            return self._jit_build_state(params)

    @property
    def param_count(self) -> int:
        """Total trainable parameter count, from the abstract state
        tree (no device math) — the N in the 6·N·T step-FLOPs
        estimate."""
        total = 0
        for leaf in jax.tree.leaves(self.state_shapes.params):
            shape = getattr(leaf, "shape", None)
            if shape is None:
                continue
            n = 1
            for d in shape:
                n *= d
            total += n
        return total

    def step_flops(self, batch: int, seq: int) -> float:
        """Model FLOPs one `step()` call spends on a [batch, seq]
        token block (6·N·T) — what the elastic worker feeds the
        GoodputLedger for MFU/tokens-per-second accounting."""
        return estimate_step_flops(self.param_count, batch * seq)

    def opt_state_bytes(self, *, per_replica: bool = True) -> int:
        """Optimizer-state footprint in bytes: global, or what a single
        device actually holds (`per_replica`) — the number ZeRO drives
        down ~data-axis-fold while the global total stays fixed."""
        total = 0
        shapes = jax.tree.leaves(self.state_shapes.opt_state)
        shardings = jax.tree.leaves(
            self.opt_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding))
        for leaf, sh in zip(shapes, shardings):
            shape = getattr(leaf, "shape", None)
            if shape is None:
                continue
            nbytes = leaf.dtype.itemsize
            for d in shape:
                nbytes *= d
            if per_replica:
                ways = 1
                for axis in sharding_lib._spec_axes(sh.spec):
                    ways *= self.mesh.shape.get(axis, 1)
                nbytes = -(-nbytes // max(ways, 1))  # ceil per-shard
            total += nbytes
        return total

    def step(self, state: TrainState, tokens, targets, mask=None):
        if mask is None:
            mask = jnp.ones_like(tokens, dtype=jnp.float32)
        if self.tc.grad_accum > 1 \
                and tokens.shape[0] % self.tc.grad_accum:
            raise ValueError(
                f"batch {tokens.shape[0]} not divisible by grad_accum "
                f"{self.tc.grad_accum}")
        # No added blocking: steady-state timings measure dispatch (the
        # async-dispatch pipelining is the perf contract). The FIRST call
        # is synchronous through trace+compile, so it alone is a
        # meaningful wall measurement → train_compile_seconds.
        compiling = not self._stepped
        t0 = time.perf_counter()
        if self._last_step_end is not None:
            # Everything between consecutive step() calls — input
            # pipeline, checkpoint writes, eval, logging — is the
            # trainer's host gap.
            self.profiler.record("host_gap", t0 - self._last_step_end)
        with self.tracer.span("train.step", batch=int(tokens.shape[0]),
                              compile=compiling):
            with mesh_lib.set_mesh(self.mesh):
                with self.profiler.phase(
                        "step", tokens=int(tokens.shape[0])
                        * int(tokens.shape[1])):
                    out = self._jit_step(state, tokens, targets, mask)
        dt = time.perf_counter() - t0
        self._last_step_end = time.perf_counter()
        self.step_seconds.observe(dt)
        if compiling:
            self._stepped = True
            self.compile_seconds.observe(dt)
        return out


def _opt_state_shardings(opt_shapes, params_shapes, param_shardings, mesh):
    """Opt-state leaves that mirror a param (optax mu/nu are copies of the
    param pytree) get that param's sharding; everything else (step counts,
    scalars) is replicated.

    Matching is by tree-path suffix + shape, NOT shape alone: for e.g.
    Llama-8B, wq [L, 4096, 4096] and wo [L, 4096, 4096] share a shape but
    have transposed shardings — a shape-only match would silently shard
    wo's adam moments wrong and force per-step resharding over ICI.
    """
    param_by_path: dict[tuple, Any] = {}
    # jax.tree.leaves_with_path only landed in 0.4.35+aliases; the
    # tree_util spelling works across the versions we support.
    flat_params = jax.tree_util.tree_leaves_with_path(params_shapes)
    flat_shard = jax.tree.leaves(param_shardings)
    for (path, leaf), sh in zip(flat_params, flat_shard):
        param_by_path[tuple(str(p) for p in path)] = (leaf.shape, sh)

    replicated = NamedSharding(mesh, P())
    max_suffix = max((len(p) for p in param_by_path), default=0)

    def pick(opt_path, leaf):
        if not hasattr(leaf, "shape") or len(leaf.shape) == 0:
            return replicated
        keys = tuple(str(p) for p in opt_path)
        # Longest path-suffix of the opt leaf that names a param leaf.
        for n in range(min(len(keys), max_suffix), 0, -1):
            hit = param_by_path.get(keys[-n:])
            if hit is not None:
                shape, sh = hit
                if shape == leaf.shape:
                    return sh
                break
        return replicated

    return jax.tree_util.tree_map_with_path(pick, opt_shapes)
