"""Training loop layer: sharded train step, optimizer, data."""

from kubeflow_tpu.train.trainer import (
    TrainState,
    Trainer,
    TrainConfig,
    cross_entropy_loss,
)
from kubeflow_tpu.train.checkpoint import CheckpointConfig, Checkpointer
