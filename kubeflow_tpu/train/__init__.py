"""Training loop layer: sharded train step, optimizer, data."""

from kubeflow_tpu.train.trainer import (
    TrainState,
    Trainer,
    TrainConfig,
    cross_entropy_loss,
)
from kubeflow_tpu.train.checkpoint import CheckpointConfig, Checkpointer
from kubeflow_tpu.train.elastic import (
    ElasticCoordinator,
    WorkerConfig,
    create_coordinator_app,
    resize_state,
    run_worker,
)
from kubeflow_tpu.train.lora import (
    LoraConfig,
    init_lora,
    lora_freeze_labels,
    lora_logical_axes,
    lora_loss_fn,
    lora_train_tree,
    merge_lora,
)
