"""Checkpoint / resume: Orbax-backed sharded train-state persistence.

The reference has no training checkpoints; its closest analog is the
workspace-PVC-survives-cull pattern (SURVEY.md §5 "Checkpoint / resume":
JWA creates PVCs before the CR, culling sets replicas 0 without deleting
the CR, PATCH restarts it — reference
`components/crud-web-apps/jupyter/backend/apps/default/routes/post.py:48-67`,
`components/notebook-controller/pkg/culler/culler.go:36-40`). Here the
first-class resume path is an Orbax checkpoint of the full sharded
TrainState: each host writes only its shards (OCDBT), restore reapplies
the trainer's NamedShardings so a resumed job lands exactly where the
mesh wants it — no host-side gather, no resharding traffic on ICI.

Layout per step: `<dir>/<step>/state/` (Orbax OCDBT tree) plus a
`metadata` entry carrying the user-supplied run config for provenance.

Crash safety (ISSUE 11): each fully-durable step dir additionally gets a
`COMMITTED` marker, written only after the (possibly async) Orbax write
has finished. Restore resolves "latest" through the markers, so a step
dir left behind by a SIGKILL mid-save is SKIPPED with a log line instead
of being restored half-written. Resize-on-restore: restore targets the
CURRENT trainer's shardings, so a run saved at N virtual replicas (mesh
data-axis size) restores cleanly at M != N — the saved replica count is
recorded in run_metadata and the resize is logged.
"""

from __future__ import annotations

import atexit
import dataclasses
import logging
import os
import signal
import time
from typing import Any, Mapping

import jax
import orbax.checkpoint as ocp
from etils import epath

from kubeflow_tpu import obs
from kubeflow_tpu.train.trainer import Trainer, TrainState

STATE_ITEM = "state"
META_ITEM = "run_metadata"
DATA_ITEM = "data_state"
COMMIT_MARKER = "COMMITTED"

log = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    directory: str
    save_interval_steps: int = 1000
    max_to_keep: int | None = 3
    # Async saves overlap the device→disk copy with the next train steps;
    # close()/wait() must run before the process exits.
    enable_async: bool = True
    # A trained tokenizer to carry WITH the checkpoint (e.g. the
    # tools/prepare_data.py output's tokenizer.json): copied once to
    # <directory>/tokenizer.json on the first save, which is exactly
    # where the serving CLI's `--tokenizer auto` looks — without this
    # the prepare -> train -> serve loop drops its tokenizer at the
    # last hop and text mode silently degrades to bytes.
    tokenizer_path: str = ""
    # Register SIGTERM + atexit handlers that drain the async save
    # queue (wait + close) before the process dies, so a preempted
    # trainer's in-flight checkpoint still commits. Off by default:
    # library users (tests, notebooks) shouldn't have their process
    # signal disposition changed by constructing an object.
    install_crash_handlers: bool = False


class Checkpointer:
    """Save/restore a Trainer's TrainState with its shardings.

    Usage:
        ckpt = Checkpointer(CheckpointConfig(dir), trainer)
        state = ckpt.restore_or_init(jax.random.key(0))
        for ...:
            state, loss = trainer.step(state, ...)
            ckpt.maybe_save(state)
        ckpt.close()
    """

    def __init__(self, config: CheckpointConfig, trainer: Trainer,
                 run_metadata: Mapping[str, Any] | None = None,
                 registry=None):
        self.config = config
        self.trainer = trainer
        self.run_metadata = dict(run_metadata or {})
        opts = ocp.CheckpointManagerOptions(
            save_interval_steps=config.save_interval_steps,
            max_to_keep=config.max_to_keep,
            enable_async_checkpointing=config.enable_async,
        )
        self._mgr = ocp.CheckpointManager(
            config.directory, options=opts,
            item_names=(STATE_ITEM, META_ITEM, DATA_ITEM),
        )
        self._pending_commits: set[int] = set()
        self._closed = False
        self._handlers_installed = False
        reg = registry if registry is not None else obs.default_registry()
        # one catalog site (train.goodput.checkpoint_histograms) owns
        # the name/help/bucket definitions — the coordinator zero-seeds
        # the same families and the two may not drift
        from kubeflow_tpu.train.goodput import checkpoint_histograms

        self.save_seconds, self.restore_seconds = \
            checkpoint_histograms(reg)
        if config.install_crash_handlers:
            self.install_crash_handlers()

    @property
    def virtual_replicas(self) -> int:
        """The trainer mesh's data-axis size — the replica count a
        checkpoint saved through this Checkpointer is stamped with."""
        return int(self.trainer.mesh.shape.get("data", 1))

    # -- save ------------------------------------------------------------

    def save(self, state: TrainState, *, force: bool = False,
             data_state: Mapping[str, Any] | None = None) -> bool:
        """`data_state` rides along as a JSON item — pass the loader's
        `state_dict()` (the batch ticket) so a resumed run continues
        the EXACT batch stream instead of restarting the epoch (the
        loaders' start_ticket kwarg is the other half)."""
        step = int(jax.device_get(state.step))
        t0 = time.perf_counter()
        # The previous async save is durable once wait() returns (Orbax
        # serializes saves anyway, so this barrier is ~free) — only THEN
        # may its COMMITTED marker appear.
        self._flush_commits()
        step_dir = self.step_path(step)
        if step_dir.exists():
            if self._is_committed(step):
                # Replaying up to an already-durable step (post-restore
                # catch-up) — nothing to write.
                log.info("step %d already committed under %s — "
                         "skipping save", step, self.config.directory)
                return False
            # Garbage from a crashed incarnation (its COMMITTED marker
            # never appeared): clear it or Orbax refuses the step.
            log.warning(
                "removing stale uncommitted dir for step %d under %s "
                "before re-save", step, self.config.directory)
            step_dir.rmtree()
            reload_fn = getattr(self._mgr, "reload", None)
            if callable(reload_fn):
                reload_fn()
        meta = dict(self.run_metadata)
        meta["virtual_replicas"] = self.virtual_replicas
        saved = self._mgr.save(
            step,
            args=ocp.args.Composite(**{
                STATE_ITEM: ocp.args.StandardSave(_to_tree(state)),
                META_ITEM: ocp.args.JsonSave(meta),
                DATA_ITEM: ocp.args.JsonSave(dict(data_state or {})),
            }),
            force=force,
        )
        if saved:
            if self.config.enable_async:
                self._pending_commits.add(step)
            else:
                self._commit(step)
            if self.config.tokenizer_path:
                self._carry_tokenizer()
            self.save_seconds.observe(time.perf_counter() - t0)
        return saved

    def _commit(self, step: int) -> None:
        marker = self.step_path(step) / COMMIT_MARKER
        if marker.parent.exists():
            marker.write_text(f"{step}\n")

    def _flush_commits(self) -> None:
        """Write COMMITTED markers for saves whose async write finished."""
        if not self._pending_commits:
            return
        self._mgr.wait_until_finished()
        on_disk = set(self._mgr.all_steps())
        for step in sorted(self._pending_commits):
            if step in on_disk:
                self._commit(step)
        self._pending_commits.clear()

    def _carry_tokenizer(self) -> None:
        """Copy the configured tokenizer to <dir>/tokenizer.json once
        (epath: the checkpoint dir can be gs://)."""
        dst = epath.Path(self.config.directory) / "tokenizer.json"
        if not dst.exists():
            dst.write_text(
                epath.Path(self.config.tokenizer_path).read_text())

    def maybe_save(self, state: TrainState, *,
                   data_state: Mapping[str, Any] | None = None) -> bool:
        """Save iff the manager's save_interval policy says so."""
        return self.save(state, force=False, data_state=data_state)

    # -- restore ---------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def step_path(self, step: int) -> epath.Path:
        """The directory one step's checkpoint lives in — the ONE
        derivation site for <dir>/<step> (save, commit markers, restore
        side channels, and the rollout publish hook all go through
        here)."""
        return epath.Path(self.config.directory) / str(step)

    def latest_committed_path(self) -> epath.Path | None:
        """Directory of the newest COMMITTED step, or None before the
        first durable save. What the elastic chief publishes to
        `POST /fleet/versions` (ISSUE 18) and what resize-on-restore
        inspects — never an uncommitted crash leftover."""
        step = self.latest_committed_step()
        return None if step is None else self.step_path(step)

    def _is_committed(self, step: int) -> bool:
        return (self.step_path(step) / COMMIT_MARKER).exists()

    def committed_steps(self) -> list[int]:
        """Steps with a durable COMMITTED marker, ascending. Dirs left
        by a crash mid-save carry no marker and are excluded (and
        logged) — they are what restore must never touch."""
        out: list[int] = []
        for step in sorted(self._mgr.all_steps()):
            if self._is_committed(step):
                out.append(step)
            else:
                log.warning(
                    "checkpoint step %d under %s has no %s marker "
                    "(crash mid-save?) — skipping uncommitted step",
                    step, self.config.directory, COMMIT_MARKER)
        return out

    def latest_committed_step(self) -> int | None:
        steps = self.committed_steps()
        return steps[-1] if steps else None

    def abstract_state(self) -> dict[str, Any]:
        """ShapeDtypeStructs + NamedShardings describing the state tree."""
        def abstr(leaf, sh):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

        return jax.tree.map(
            abstr,
            _to_tree(self.trainer.state_shapes),
            _to_tree(self.trainer.state_shardings),
        )

    def restore(self, step: int | None = None) -> TrainState:
        """Restore onto the CURRENT trainer's mesh/shardings.

        `step=None` resolves through the COMMITTED markers and falls
        back to the next-older committed step if the newest one fails
        to deserialize (partial write that still got a dir); an
        explicit `step` is restored exactly or raises. Works across
        replica counts: Orbax reshards the saved global arrays onto
        whatever NamedShardings `abstract_state()` carries now.
        """
        self._flush_commits()
        if step is not None:
            candidates = [step]
        else:
            candidates = list(reversed(self.committed_steps()))
            if not candidates and self.latest_step() is not None:
                raise FileNotFoundError(
                    f"checkpoints exist under {self.config.directory} "
                    "but none carry a COMMITTED marker — all were "
                    "interrupted mid-save")
        if not candidates:
            raise FileNotFoundError(
                f"no checkpoint under {self.config.directory}"
            )
        t0 = time.perf_counter()
        last_err: Exception | None = None
        for i, cand in enumerate(candidates):
            try:
                restored = self._mgr.restore(
                    cand,
                    args=ocp.args.Composite(**{
                        STATE_ITEM: ocp.args.StandardRestore(
                            self.abstract_state()),
                    }),
                )
            except Exception as e:  # noqa: BLE001 — fall back, then re-raise
                last_err = e
                if step is not None or i + 1 >= len(candidates):
                    raise
                log.warning(
                    "committed checkpoint step %d failed to restore "
                    "(%s) — falling back to step %d",
                    cand, e, candidates[i + 1])
                continue
            self.restore_seconds.observe(time.perf_counter() - t0)
            self._log_resize(cand)
            return _from_tree(restored[STATE_ITEM])
        raise last_err  # pragma: no cover — loop always returns/raises

    def _log_resize(self, step: int) -> None:
        try:
            meta = self.restore_metadata(step)
        except Exception:  # noqa: BLE001 — provenance only, never fatal
            return
        saved = meta.get("virtual_replicas")
        if saved and int(saved) != self.virtual_replicas:
            log.info(
                "resize-on-restore: step %d (%s) was saved at %d "
                "virtual replicas, restored at %d (optimizer state "
                "re-partitioned over the new data axis)",
                step, self.step_path(step), int(saved),
                self.virtual_replicas)

    def _restore_json_item(self, item: str, step: int | None,
                           *, missing_ok: bool) -> dict[str, Any]:
        """Shared step resolution + single-JSON-item restore for the
        metadata and data-state side channels. `missing_ok` absorbs
        only the ABSENT-item case (a checkpoint written before the
        item existed) — a present-but-corrupt item raises, because
        silently restoring {} would e.g. restart the data stream at
        ticket 0 with no error (the failure the item exists to
        prevent)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return {}
        if missing_ok:
            # epath, not os.path: checkpoint dirs can be remote
            # (gs://...), where os.path.isdir is always False and the
            # probe would silently report every item absent — restarting
            # a resumed data stream at ticket 0, the exact failure this
            # item exists to prevent.
            item_dir = self.step_path(step) / item
            if not item_dir.exists():
                return {}
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(**{item: ocp.args.JsonRestore()}),
        )
        return dict(restored[item] or {})

    def restore_metadata(self, step: int | None = None) -> dict[str, Any]:
        return self._restore_json_item(META_ITEM, step, missing_ok=False)

    def restore_data_state(self, step: int | None = None) -> dict[str, Any]:
        """The loader position saved beside `step` ({} when the
        checkpoint predates data-state tracking or none was passed)."""
        return self._restore_json_item(DATA_ITEM, step, missing_ok=True)

    def restore_or_init(self, rng: jax.Array) -> TrainState:
        """The resume entry point: latest COMMITTED checkpoint if
        present, else fresh init (a directory holding only interrupted
        saves logs and initializes rather than crash-looping)."""
        self._flush_commits()
        if self.latest_committed_step() is not None:
            return self.restore()
        if self.latest_step() is not None:
            log.warning(
                "no committed checkpoint under %s (only interrupted "
                "saves) — initializing fresh state",
                self.config.directory)
        return self.trainer.init(rng)

    # -- lifecycle -------------------------------------------------------

    def wait(self) -> None:
        self._mgr.wait_until_finished()
        self._flush_commits()

    def close(self) -> None:
        if self._closed:
            return
        self._mgr.wait_until_finished()
        self._flush_commits()
        self._mgr.close()
        self._closed = True

    def install_crash_handlers(self) -> None:
        """Drain + commit on SIGTERM and interpreter exit, chaining any
        prior SIGTERM disposition. Idempotent. A SIGKILL (the chaos
        harness's weapon) of course bypasses this — that is what the
        COMMITTED markers are for."""
        if self._handlers_installed:
            return
        self._handlers_installed = True
        atexit.register(self._drain_quietly)
        prev = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            self._drain_quietly()
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            # not the main thread — atexit alone still drains
            log.debug("SIGTERM handler not installed (non-main thread)")

    def _drain_quietly(self) -> None:
        try:
            self.close()
        except Exception:  # noqa: BLE001 — dying anyway; don't mask the signal
            log.exception("checkpoint drain on shutdown failed")


def _to_tree(state) -> dict[str, Any]:
    """TrainState → plain dict so Orbax sees stable string keys."""
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "step": state.step,
    }


def _from_tree(tree: Mapping[str, Any]) -> TrainState:
    return TrainState(tree["params"], tree["opt_state"], tree["step"])
