"""Checkpoint / resume: Orbax-backed sharded train-state persistence.

The reference has no training checkpoints; its closest analog is the
workspace-PVC-survives-cull pattern (SURVEY.md §5 "Checkpoint / resume":
JWA creates PVCs before the CR, culling sets replicas 0 without deleting
the CR, PATCH restarts it — reference
`components/crud-web-apps/jupyter/backend/apps/default/routes/post.py:48-67`,
`components/notebook-controller/pkg/culler/culler.go:36-40`). Here the
first-class resume path is an Orbax checkpoint of the full sharded
TrainState: each host writes only its shards (OCDBT), restore reapplies
the trainer's NamedShardings so a resumed job lands exactly where the
mesh wants it — no host-side gather, no resharding traffic on ICI.

Layout per step: `<dir>/<step>/state/` (Orbax OCDBT tree) plus a
`metadata` entry carrying the user-supplied run config for provenance.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
import orbax.checkpoint as ocp
from etils import epath

from kubeflow_tpu.train.trainer import Trainer, TrainState

STATE_ITEM = "state"
META_ITEM = "run_metadata"
DATA_ITEM = "data_state"


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    directory: str
    save_interval_steps: int = 1000
    max_to_keep: int | None = 3
    # Async saves overlap the device→disk copy with the next train steps;
    # close()/wait() must run before the process exits.
    enable_async: bool = True
    # A trained tokenizer to carry WITH the checkpoint (e.g. the
    # tools/prepare_data.py output's tokenizer.json): copied once to
    # <directory>/tokenizer.json on the first save, which is exactly
    # where the serving CLI's `--tokenizer auto` looks — without this
    # the prepare -> train -> serve loop drops its tokenizer at the
    # last hop and text mode silently degrades to bytes.
    tokenizer_path: str = ""


class Checkpointer:
    """Save/restore a Trainer's TrainState with its shardings.

    Usage:
        ckpt = Checkpointer(CheckpointConfig(dir), trainer)
        state = ckpt.restore_or_init(jax.random.key(0))
        for ...:
            state, loss = trainer.step(state, ...)
            ckpt.maybe_save(state)
        ckpt.close()
    """

    def __init__(self, config: CheckpointConfig, trainer: Trainer,
                 run_metadata: Mapping[str, Any] | None = None):
        self.config = config
        self.trainer = trainer
        self.run_metadata = dict(run_metadata or {})
        opts = ocp.CheckpointManagerOptions(
            save_interval_steps=config.save_interval_steps,
            max_to_keep=config.max_to_keep,
            enable_async_checkpointing=config.enable_async,
        )
        self._mgr = ocp.CheckpointManager(
            config.directory, options=opts,
            item_names=(STATE_ITEM, META_ITEM, DATA_ITEM),
        )

    # -- save ------------------------------------------------------------

    def save(self, state: TrainState, *, force: bool = False,
             data_state: Mapping[str, Any] | None = None) -> bool:
        """`data_state` rides along as a JSON item — pass the loader's
        `state_dict()` (the batch ticket) so a resumed run continues
        the EXACT batch stream instead of restarting the epoch (the
        loaders' start_ticket kwarg is the other half)."""
        step = int(jax.device_get(state.step))
        saved = self._mgr.save(
            step,
            args=ocp.args.Composite(**{
                STATE_ITEM: ocp.args.StandardSave(_to_tree(state)),
                META_ITEM: ocp.args.JsonSave(self.run_metadata),
                DATA_ITEM: ocp.args.JsonSave(dict(data_state or {})),
            }),
            force=force,
        )
        if saved and self.config.tokenizer_path:
            self._carry_tokenizer()
        return saved

    def _carry_tokenizer(self) -> None:
        """Copy the configured tokenizer to <dir>/tokenizer.json once
        (epath: the checkpoint dir can be gs://)."""
        dst = epath.Path(self.config.directory) / "tokenizer.json"
        if not dst.exists():
            dst.write_text(
                epath.Path(self.config.tokenizer_path).read_text())

    def maybe_save(self, state: TrainState, *,
                   data_state: Mapping[str, Any] | None = None) -> bool:
        """Save iff the manager's save_interval policy says so."""
        return self.save(state, force=False, data_state=data_state)

    # -- restore ---------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def abstract_state(self) -> dict[str, Any]:
        """ShapeDtypeStructs + NamedShardings describing the state tree."""
        def abstr(leaf, sh):
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

        return jax.tree.map(
            abstr,
            _to_tree(self.trainer.state_shapes),
            _to_tree(self.trainer.state_shardings),
        )

    def restore(self, step: int | None = None) -> TrainState:
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint under {self.config.directory}"
            )
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(**{
                STATE_ITEM: ocp.args.StandardRestore(self.abstract_state()),
            }),
        )
        return _from_tree(restored[STATE_ITEM])

    def _restore_json_item(self, item: str, step: int | None,
                           *, missing_ok: bool) -> dict[str, Any]:
        """Shared step resolution + single-JSON-item restore for the
        metadata and data-state side channels. `missing_ok` absorbs
        only the ABSENT-item case (a checkpoint written before the
        item existed) — a present-but-corrupt item raises, because
        silently restoring {} would e.g. restart the data stream at
        ticket 0 with no error (the failure the item exists to
        prevent)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return {}
        if missing_ok:
            # epath, not os.path: checkpoint dirs can be remote
            # (gs://...), where os.path.isdir is always False and the
            # probe would silently report every item absent — restarting
            # a resumed data stream at ticket 0, the exact failure this
            # item exists to prevent.
            item_dir = epath.Path(self.config.directory) / str(step) / item
            if not item_dir.exists():
                return {}
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(**{item: ocp.args.JsonRestore()}),
        )
        return dict(restored[item] or {})

    def restore_metadata(self, step: int | None = None) -> dict[str, Any]:
        return self._restore_json_item(META_ITEM, step, missing_ok=False)

    def restore_data_state(self, step: int | None = None) -> dict[str, Any]:
        """The loader position saved beside `step` ({} when the
        checkpoint predates data-state tracking or none was passed)."""
        return self._restore_json_item(DATA_ITEM, step, missing_ok=True)

    def restore_or_init(self, rng: jax.Array) -> TrainState:
        """The resume entry point: latest checkpoint if present, else init."""
        if self.latest_step() is not None:
            return self.restore()
        return self.trainer.init(rng)

    # -- lifecycle -------------------------------------------------------

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def _to_tree(state) -> dict[str, Any]:
    """TrainState → plain dict so Orbax sees stable string keys."""
    return {
        "params": state.params,
        "opt_state": state.opt_state,
        "step": state.step,
    }


def _from_tree(tree: Mapping[str, Any]) -> TrainState:
    return TrainState(tree["params"], tree["opt_state"], tree["step"])
