"""Elastic fault-tolerant trainer fleet: membership, resize, restart.

The serving fleet already knows how to keep a replica set alive
(fleet.registry: heartbeats, staleness sweep, dead detection). This
module points that same machinery at TRAINER replicas and closes the
loop the paper's trainer story needs: when a trainer dies mid-run, the
surviving replicas restart from the last COMMITTED checkpoint at the
new replica count — resize-on-restore (train.checkpoint) re-partitions
the ZeRO-sharded optimizer state over the smaller (or larger) data
axis, and training continues with identical global math.

Roles:
  * `ElasticCoordinator` — wraps a ReplicaRegistry; trainers register/
    heartbeat with (step, loss, phase); the coordinator decides the
    surviving world and stamps it with a monotonically increasing
    `generation`. Any membership change bumps the generation; losing a
    previously-live member also counts a restart (the survivors will
    restart from checkpoint). Exposes `train_replicas{state}`,
    `train_restarts_total` and `train_generation` on its registry.
  * `create_coordinator_app` — the aiohttp surface (register/heartbeat/
    world + /metrics) the worker subprocesses and the chaos harness
    talk to.
  * `run_worker` / `python -m kubeflow_tpu.train.elastic worker` — a
    trainer replica: replicated execution (every worker computes the
    full global step; the mesh's data axis tracks the live world size,
    which is what ZeRO partitions over), chief-only checkpoint writes,
    and in-process restart-from-checkpoint when the generation moves.
  * `resize_state` — live cross-mesh resize without a disk round trip:
    gather under the old trainer's mesh, shard under the new one
    (parallel.sharding.make_shard_and_gather_fns).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
from typing import Any, Callable, Mapping

from kubeflow_tpu import obs
from kubeflow_tpu.controlplane.metrics import Counter, Gauge, Registry
from kubeflow_tpu.fleet import registry as fleet_registry
from kubeflow_tpu.fleet.registry import STATES, ReplicaRegistry
from kubeflow_tpu.train.goodput import (
    GOODPUT_CAUSES,
    LOST_CAUSES,
    GoodputLedger,
    bind_ledger_metrics,
    checkpoint_histograms,
    goodput_metrics,
)

log = logging.getLogger(__name__)

LIVE_STATES = (fleet_registry.READY, fleet_registry.DEGRADED)

# Heartbeat phases a trainer replica reports. "saving" matters to the
# chaos harness: it is the window in which a SIGKILL lands mid-
# checkpoint-save.
PHASE_STEP = "step"
PHASE_SAVING = "saving"
PHASE_RESTORING = "restoring"
PHASE_DONE = "done"

# Everything a worker heartbeat may carry. The observatory keys
# (step_seconds, saves/save_seconds, goodput, metrics, trace) ride the
# same POST as the membership keys — one beat is both liveness and
# telemetry, so a worker that is alive is by construction observable.
HEARTBEAT_KEYS = ("step", "loss", "phase", "generation", "step_seconds",
                  "saves", "save_seconds", "goodput", "metrics", "trace")


class ElasticCoordinator:
    """Decides the surviving trainer world from heartbeats.

    Reuses ReplicaRegistry's staleness machinery verbatim; what it adds
    is trainer-shaped stats (float loss, monotonic step, phase — the
    registry's int-stat schema is serving-specific) and the generation/
    restart bookkeeping the workers key their restarts off.
    """

    def __init__(self, *, min_replicas: int = 1,
                 degraded_after_s: float = 6.0,
                 dead_after_s: float = 20.0,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None,
                 slo_step_time_s: float = 2.0,
                 slo_checkpoint_save_s: float = 10.0,
                 restart_burn_hold_s: float = 5.0,
                 slo_short_window_s: float = 60.0,
                 slo_long_window_s: float = 600.0):
        self.min_replicas = int(min_replicas)
        self._registry = ReplicaRegistry(
            degraded_after_s=degraded_after_s,
            dead_after_s=dead_after_s,
            clock=clock,
        )
        self._lock = threading.Lock()
        self._clock = clock
        self._stats: dict[str, dict[str, Any]] = {}
        self._members: tuple[str, ...] = ()
        self._generation = 0
        self.registry = registry if registry is not None \
            else obs.default_registry()
        self.replicas_gauge = self.registry.get("train_replicas")
        if self.replicas_gauge is None:
            self.replicas_gauge = Gauge(
                "train_replicas",
                "Trainer replicas by health state (heartbeat-driven; "
                "dead replicas shrink the next generation's world)",
                self.registry)
        self.generation_gauge = self.registry.get("train_generation")
        if self.generation_gauge is None:
            self.generation_gauge = Gauge(
                "train_generation",
                "Monotonic world generation; bumps on any trainer "
                "membership change", self.registry)
        self.restarts_total = self.registry.get("train_restarts_total")
        if self.restarts_total is None:
            self.restarts_total = Counter(
                "train_restarts_total",
                "Fleet-wide restart-from-checkpoint events (a "
                "previously-live trainer left the world)", self.registry)
        for s in STATES:
            self.replicas_gauge.set(0.0, state=s)
        self.generation_gauge.set(0.0)
        self.restarts_total.inc(0.0)
        # The full train_* metric catalog lives on the coordinator's
        # registry so one /metrics scrape sees every family zero-seeded
        # (ci.obs_check train / train-obs) even before any checkpoint
        # I/O or worker telemetry happened.
        checkpoint_histograms(self.registry)
        # -- goodput observatory (ISSUE 14) --------------------------------
        # Worker labels pass a guard so a flapping fleet cannot mint
        # unbounded timeseries; past the cap stragglers collapse into
        # the "other" bucket (which we zero-seed so the family exists).
        self._worker_guard = obs.LabelGuard(max_values=32)
        self.worker_step_seconds = self.registry.get(
            "train_worker_step_seconds")
        if self.worker_step_seconds is None:
            self.worker_step_seconds = Gauge(
                "train_worker_step_seconds",
                "Latest steady-state step wall time per worker "
                "(straggler forensics; 0 = no step yet or worker lost)",
                self.registry)
        self.worker_step_seconds.set(0.0, worker=obs.OVERFLOW_LABEL)
        self.straggler_ratio = self.registry.get("train_straggler_ratio")
        if self.straggler_ratio is None:
            self.straggler_ratio = Gauge(
                "train_straggler_ratio",
                "Slowest / median live-worker step time (1.0 = uniform "
                "fleet; the gang runs at the slowest member's pace)",
                self.registry)
        self.straggler_ratio.set(0.0)
        self.goodput_fraction = self.registry.get("train_goodput_fraction")
        if self.goodput_fraction is None:
            self.goodput_fraction = Gauge(
                "train_goodput_fraction",
                "Fleet productive worker-seconds over all booked "
                "worker-seconds, cumulative across worker incarnations",
                self.registry)
        self.goodput_fraction.set(0.0)
        self.replay_seconds_total = self.registry.get(
            "train_replay_seconds_total")
        if self.replay_seconds_total is None:
            self.replay_seconds_total = Counter(
                "train_replay_seconds_total",
                "Fleet worker-seconds NOT spent advancing the run, by "
                "cause (replay = re-running steps past the last "
                "committed checkpoint — the direct price of a restart)",
                self.registry)
        for _c in LOST_CAUSES:
            self.replay_seconds_total.inc(0.0, cause=_c)
        # Zero-seed the worker-side goodput families too: one scrape of
        # the coordinator (or of /elastic/metrics with zero live
        # workers) still shows the full catalog shape.
        goodput_metrics(self.registry)
        # -- train SLOs (PR 6 engine; the engine IS slo_burn_rate) ---------
        self.restart_burn_hold_s = float(restart_burn_hold_s)
        self._burn_until = 0.0
        self._saves_seen: dict[str, int] = {}
        self._goodput_last: dict[str, dict[str, float]] = {}
        self._fleet_seconds: dict[str, float] = {
            c: 0.0 for c in (*GOODPUT_CAUSES, obs.UNATTRIBUTED)}
        self.slo = obs.get_or_create_slo_engine(self.registry, [
            obs.Slo("train_step_time", 0.9,
                    threshold_s=float(slo_step_time_s),
                    description="90% of steady-state worker steps "
                                f"under {slo_step_time_s:g} s"),
            obs.Slo("train_checkpoint_save", 0.9,
                    threshold_s=float(slo_checkpoint_save_s),
                    description="90% of checkpoint saves under "
                                f"{slo_checkpoint_save_s:g} s"),
            obs.Slo("train_goodput", 0.9,
                    description="90% of goodput pulses productive: a "
                                "heartbeat interval must book at least "
                                "as many productive seconds as replay+"
                                "restore+compile+stall combined"),
            obs.Slo("train_restart_burn", 0.99,
                    description="99% of heartbeats outside a restart "
                                "hold window (a lost member burns the "
                                "budget for restart_burn_hold_s)"),
        ], short_window_s=slo_short_window_s,
           long_window_s=slo_long_window_s, clock=clock)

    # -- membership ------------------------------------------------------

    def register(self, replica_id: str, **stats) -> dict[str, Any]:
        with self._lock:
            self._registry.register(
                f"trainer://{replica_id}", replica_id=replica_id,
                models=("trainer",))
            self._stats.setdefault(replica_id, {})
            self._note(replica_id, stats)
            self._recompute()
            return self._world_locked()

    def heartbeat(self, replica_id: str, **stats) -> bool:
        """Refresh liveness + trainer stats. False for an unknown id —
        the worker must re-register (coordinator restarted)."""
        with self._lock:
            known = self._registry.heartbeat(replica_id)
            if known:
                self._note(replica_id, stats)
            self._recompute()
            return known

    def _note(self, replica_id: str, stats: Mapping[str, Any]) -> None:
        slot = self._stats.setdefault(replica_id, {})
        prev_step = slot.get("step")
        for key in HEARTBEAT_KEYS:
            if stats.get(key) is not None:
                slot[key] = stats[key]
        # straggler forensics: latest steady step wall per worker, and
        # one step-time SLO event per step ADVANCE (heartbeats repeat
        # the latest value between steps; re-recording it would drown
        # the burn windows in duplicates)
        ss = stats.get("step_seconds")
        if ss is not None:
            self.worker_step_seconds.set(
                float(ss), worker=self._worker_guard.admit(replica_id))
            if stats.get("step") is not None \
                    and stats.get("step") != prev_step:
                self.slo.observe("train_step_time", float(ss))
        # checkpoint-save SLO: once per completed save (the `saves`
        # counter dedups the repeated heartbeat snapshots)
        saves = stats.get("saves")
        if saves is not None and stats.get("save_seconds") is not None \
                and int(saves) > self._saves_seen.get(replica_id, 0):
            self._saves_seen[replica_id] = int(saves)
            self.slo.observe("train_checkpoint_save",
                             float(stats["save_seconds"]))
        gp = stats.get("goodput")
        if isinstance(gp, Mapping):
            self._ingest_goodput(replica_id, gp)
        # restart-burn pulse: every heartbeat inside the hold window
        # after a lost member is a bad event — the burn rate stays hot
        # for restart_burn_hold_s, then recovers
        self.slo.record("train_restart_burn",
                        self._clock() >= self._burn_until)

    def _ingest_goodput(self, replica_id: str,
                        gp: Mapping[str, Any]) -> None:
        """Fold one worker's cumulative ledger snapshot into the fleet
        cause totals via clamped deltas. A restarted worker's ledger
        begins at zero — detected by its wall clock rewinding — so
        every incarnation's seconds count exactly once."""
        secs = gp.get("seconds")
        if not isinstance(secs, Mapping):
            return
        wall = float(gp.get("wall_seconds") or 0.0)
        last = self._goodput_last.get(replica_id)
        if last is None or wall < last.get("_wall", 0.0) - 1e-6:
            last = {"_wall": 0.0}
        deltas: dict[str, float] = {}
        for c in (*GOODPUT_CAUSES, obs.UNATTRIBUTED):
            v = float(secs.get(c) or 0.0)
            deltas[c] = max(v - last.get(c, 0.0), 0.0)
            last[c] = max(v, last.get(c, 0.0))
        last["_wall"] = wall
        self._goodput_last[replica_id] = last
        for c, d in deltas.items():
            self._fleet_seconds[c] += d
            if d > 0 and c in LOST_CAUSES:
                self.replay_seconds_total.inc(d, cause=c)
        booked = sum(self._fleet_seconds.values())
        if booked > 0:
            self.goodput_fraction.set(
                self._fleet_seconds["productive"] / booked)
        # goodput pulse: this interval's productive seconds must cover
        # its hard overhead (replay/restore/compile/stall; save and
        # idle are normal operation and have their own signals)
        hard = (deltas["replay"] + deltas["checkpoint_restore"]
                + deltas["compile"] + deltas["stall"])
        if deltas["productive"] > 0 or hard > 0:
            self.slo.record("train_goodput",
                            deltas["productive"] >= hard)

    def sweep(self) -> None:
        with self._lock:
            self._recompute()

    def evict(self, replica_id: str | None = None) -> dict[str, Any]:
        """Evict one worker from the gang — the straggler actuator the
        fleet controller fires on `train_straggler_ratio` burn. With no
        `replica_id` the coordinator picks its own straggler: the live
        member with the slowest latest step. Eviction is just a
        deregister + recompute, so it rides the existing generation
        bump: survivors see the new generation on their next heartbeat
        and resize; the evicted worker's next heartbeat gets
        `known=False` and it rejoins as a fresh member (a slow HOST
        stays slow and gets evicted again; a transient straggler gets a
        second chance). Raises KeyError for an unknown id and
        RuntimeError when eviction would drop the gang below
        `min_replicas` — the controller books that as actuator_failed
        rather than stalling the whole job."""
        with self._lock:
            self._recompute()
            if len(self._members) <= self.min_replicas:
                raise RuntimeError(
                    f"eviction would drop the gang below min_replicas="
                    f"{self.min_replicas} (members: {len(self._members)})")
            if replica_id is None:
                slowest, slowest_ss = None, 0.0
                for rid in self._members:
                    ss = self._stats.get(rid, {}).get("step_seconds")
                    if ss is not None and float(ss) > slowest_ss:
                        slowest, slowest_ss = rid, float(ss)
                if slowest is None:
                    raise RuntimeError(
                        "no member has reported a step time yet — "
                        "nothing to call a straggler")
                replica_id = slowest
            elif replica_id not in self._members:
                raise KeyError(f"unknown member {replica_id!r}")
            self._registry.deregister(replica_id)
            self._recompute()
            log.warning("trainer eviction: %s removed (generation %d)",
                        replica_id, self._generation)
            world = self._world_locked()
            world["evicted"] = replica_id
            return world

    def _recompute(self) -> None:
        self._registry.sweep()
        live = tuple(sorted(
            rep.id for rep in self._registry.replicas()
            if rep.state in LIVE_STATES))
        if live != self._members:
            lost = set(self._members) - set(live)
            self._generation += 1
            if lost:
                self.restarts_total.inc()
                # open the restart-burn window: heartbeats record bad
                # until it closes, so slo_burn_rate{slo=
                # train_restart_burn} spikes for the hold duration
                self._burn_until = self._clock() + self.restart_burn_hold_s
                self.slo.record("train_restart_burn", False)
                for rid in lost:
                    self.worker_step_seconds.set(
                        0.0, worker=self._worker_guard.admit(rid))
                log.warning(
                    "trainer world change: lost %s, generation %d -> "
                    "world %s (survivors restart from last committed "
                    "checkpoint)", sorted(lost), self._generation, live)
            else:
                log.info("trainer world grew to %s (generation %d)",
                         live, self._generation)
            self._members = live
        for state, n in self._registry.counts().items():
            self.replicas_gauge.set(float(n), state=state)
        self.generation_gauge.set(float(self._generation))
        # straggler ratio over the LIVE members that have stepped:
        # slowest / median latest step time (1.0 = uniform; a worker
        # with no steps yet simply isn't in the sample)
        vals = []
        for rid in self._members:
            ss = self._stats.get(rid, {}).get("step_seconds")
            if ss is not None and float(ss) > 0:
                vals.append(float(ss))
        if vals:
            med = obs.sample_quantile(vals, 0.5)
            self.straggler_ratio.set(
                max(vals) / med if med and med > 0 else 0.0)
        else:
            self.straggler_ratio.set(0.0)

    # -- world view ------------------------------------------------------

    def _world_locked(self, include_stats: bool = False) -> dict[str, Any]:
        world: dict[str, Any] = {
            "generation": self._generation,
            "members": list(self._members),
            "world_size": len(self._members),
            "min_replicas": self.min_replicas,
            "ready": len(self._members) >= self.min_replicas,
            "chief": self._members[0] if self._members else None,
            # per-member progress rides on every response: workers use
            # it for soft lockstep (never run ahead of the slowest live
            # member by more than a couple of steps)
            "steps": {
                rid: self._stats.get(rid, {}).get("step")
                for rid in self._members
            },
            "phases": {
                rid: self._stats.get(rid, {}).get("phase")
                for rid in self._members
            },
            "step_seconds": {
                rid: self._stats.get(rid, {}).get("step_seconds")
                for rid in self._members
            },
            # fleet cause totals accumulate across worker incarnations
            # AND deaths — the goodput summary survives the workers
            # (the chaos harness reads it after an arm's fleet exits)
            "goodput": {
                "seconds": dict(self._fleet_seconds),
                "fraction": (
                    self._fleet_seconds["productive"]
                    / sum(self._fleet_seconds.values())
                    if sum(self._fleet_seconds.values()) > 0 else 0.0),
            },
        }
        if include_stats:
            world["replicas"] = {
                rid: {k: v for k, v in self._stats.get(rid, {}).items()
                      if k not in ("metrics", "trace")}
                for rid in self._members
            }
        return world

    def world(self, include_stats: bool = False) -> dict[str, Any]:
        with self._lock:
            self._recompute()
            return self._world_locked(include_stats)

    # -- observatory surfaces ---------------------------------------------

    def federated_metrics(self) -> str:
        """One exposition for the whole fleet: the coordinator's own
        registry plus every LIVE member's latest heartbeat exposition,
        merged by obs.federate (counters/gauges sum; histograms merge
        on the union bucket grid; a member with no exposition yet shows
        up as `fleet_federation_up{replica} 0`)."""
        with self._lock:
            self._recompute()
            scrapes: dict[str, str | None] = {
                "coordinator": self.registry.render()}
            for rid in self._members:
                scrapes[rid] = self._stats.get(rid, {}).get("metrics")
        return obs.federate(scrapes)

    def merged_traces(self) -> dict[str, Any]:
        """Every live worker's Chrome trace as its own process track
        (obs.merge_chrome_traces names the tracks by replica id)."""
        with self._lock:
            self._recompute()
            segments = []
            for rid in self._members:
                payload = self._stats.get(rid, {}).get("trace")
                if isinstance(payload, dict):
                    segments.append((rid, payload))
        return obs.merge_chrome_traces(segments)


def create_coordinator_app(coord: ElasticCoordinator):
    """The aiohttp surface workers and the chaos harness talk to."""
    from aiohttp import web

    from kubeflow_tpu.obs import endpoints as obs_endpoints

    app = web.Application()

    async def register(request):
        body = await request.json()
        world = coord.register(
            str(body["replica_id"]),
            **{k: body.get(k) for k in HEARTBEAT_KEYS})
        return web.json_response(world)

    async def heartbeat(request):
        body = await request.json()
        known = coord.heartbeat(
            str(body["replica_id"]),
            **{k: body.get(k) for k in HEARTBEAT_KEYS})
        world = coord.world()
        world["known"] = known
        return web.json_response(world)

    async def world(request):
        return web.json_response(coord.world(include_stats=True))

    async def evict(request):
        try:
            body = await request.json()
        except Exception:
            body = {}
        rid = body.get("replica_id") if isinstance(body, dict) else None
        try:
            world = coord.evict(str(rid) if rid is not None else None)
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)
        except RuntimeError as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response(world)

    async def metrics_federated(request):
        return web.Response(text=coord.federated_metrics(),
                            content_type="text/plain")

    async def traces_merged(request):
        return web.json_response(coord.merged_traces())

    app.router.add_post("/elastic/register", register)
    app.router.add_post("/elastic/heartbeat", heartbeat)
    app.router.add_post("/elastic/evict", evict)
    app.router.add_get("/elastic/world", world)
    app.router.add_get("/elastic/metrics", metrics_federated)
    app.router.add_get("/elastic/traces", traces_merged)
    obs_endpoints.mount_observability(
        app, registry=coord.registry, tracer=obs.DEFAULT_TRACER)
    return app


# -- live cross-mesh resize ---------------------------------------------


def resize_state(state, to_trainer):
    """Re-partition a TrainState onto `to_trainer`'s mesh (e.g. a
    different virtual-replica count) without a checkpoint round trip:
    gather every leaf to host under the old mesh, then place it under
    the new trainer's shardings. The two meshes never meet in one jit.
    """
    import jax

    from kubeflow_tpu.parallel import sharding as sharding_lib

    host = jax.tree.map(jax.device_get, state)
    shard_fns, _ = sharding_lib.make_shard_and_gather_fns(
        to_trainer.state_shardings)
    return jax.tree.map(lambda fn, leaf: fn(leaf), shard_fns, host)


# -- worker --------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WorkerConfig:
    coordinator_url: str
    replica_id: str
    ckpt_dir: str
    total_steps: int = 16
    save_every: int = 4
    # 12 divides by every world size up to 4 (and 6): the global batch
    # must shard over the data axis at EVERY size the world may shrink
    # or grow to, or a resize would change the global math.
    batch: int = 12
    seq: int = 16
    seed: int = 0
    heartbeat_s: float = 0.05
    # Chaos knob: sleep this long after dispatching a checkpoint save,
    # BEFORE the COMMITTED marker can be written — widens the window in
    # which a SIGKILL leaves an uncommitted step dir on disk.
    slow_save_s: float = 0.0
    loss_log: str = ""
    join_timeout_s: float = 60.0
    # Continuous-deployment hook (ISSUE 18): when set, the CHIEF
    # publishes every COMMITTED checkpoint to this fleet router's
    # `POST /fleet/versions` (version "step-<N>", source pointing at
    # ckpt_dir) so the RolloutManager can canary it onto the serving
    # fleet. Best-effort by design: a down router never blocks a save.
    publish_url: str = ""
    publish_model: str = "llama-tiny"


class _CoordinatorClient:
    """Tiny sync JSON client (urllib; workers have no aiohttp loop)."""

    def __init__(self, base_url: str):
        self.base = base_url.rstrip("/")

    def _post(self, path: str, body: dict) -> dict:
        import urllib.request

        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read().decode())

    def register(self, replica_id: str, **stats) -> dict:
        return self._post("/elastic/register",
                          {"replica_id": replica_id, **stats})

    def heartbeat(self, replica_id: str, **stats) -> dict:
        return self._post("/elastic/heartbeat",
                          {"replica_id": replica_id, **stats})


def _publish_version(wc: WorkerConfig, ckpt, published: set) -> bool:
    """Publish the newest COMMITTED checkpoint to the fleet router's
    version registry (the trainer half of the ISSUE 18 rollout loop).
    Async saves commit on the NEXT save/close, so "newest committed"
    at publish time can trail the save just dispatched — the close()
    call site catches the final one. Idempotent via `published` (steps
    already announced) and the router's own by-name idempotence;
    best-effort: any network failure is logged and swallowed, the
    training loop must never stall on a down router."""
    step = ckpt.latest_committed_step()
    if step is None or step in published:
        return False
    path = ckpt.latest_committed_path()
    body = {
        "version": f"step-{step}",
        "model": wc.publish_model,
        "step": step,
        "source": {"checkpoint": wc.ckpt_dir, "step": step,
                   "path": str(path)},
    }
    import urllib.request

    req = urllib.request.Request(
        wc.publish_url.rstrip("/") + "/fleet/versions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            ok = resp.status == 200
    except OSError as e:
        log.warning("publish of step %d to %s failed: %s", step,
                    wc.publish_url, e)
        return False
    if ok:
        published.add(step)
        log.info("published committed step %d (%s) to %s", step,
                 body["version"], wc.publish_url)
    return ok


def _deterministic_batch(cfg_vocab: int, batch: int, seq: int, seed: int,
                         step: int):
    """The data stream is a pure function of (seed, step) so every
    replica — and every post-restart incarnation at any world size —
    sees the IDENTICAL global batch. That is what makes loss-curve
    parity across elastic resizes a hard assertion instead of a vibe."""
    import numpy as np

    rng = np.random.default_rng((seed + 1) * 1_000_003 + step)
    toks = rng.integers(0, cfg_vocab, (batch, seq))
    tgts = rng.integers(0, cfg_vocab, (batch, seq))
    return toks, tgts


def _build_trainer(world_size: int, cfg, *, registry=None, tracer=None):
    import jax

    from kubeflow_tpu.models import llama
    from kubeflow_tpu.parallel import MeshSpec, create_mesh
    from kubeflow_tpu.train.trainer import TrainConfig, Trainer

    devices = jax.devices()
    if world_size > len(devices):
        raise ValueError(
            f"world size {world_size} exceeds {len(devices)} devices")
    # data axis == world size over a device SUBSET (fsdp=1): any world
    # size up to the device count forms a mesh, so a 3-replica world
    # doesn't need to divide the 8 virtual devices.
    mesh = create_mesh(MeshSpec(data=world_size, fsdp=1, tensor=1),
                       devices=devices[:world_size])
    return Trainer(
        mesh=mesh,
        apply_fn=lambda p, t: llama.apply(p, cfg, t),
        init_fn=lambda k: llama.init(k, cfg),
        logical_axes=llama.param_logical_axes(cfg),
        train_config=TrainConfig(warmup_steps=2, total_steps=1000),
        registry=registry,
        tracer=tracer,
    )


class _Heartbeater(threading.Thread):
    """Off-thread heartbeat loop: the training thread blocks for tens
    of seconds inside the first (and first-post-resize) jit compile,
    which must NOT read as death to the coordinator. The thread posts
    the latest (step, loss, phase) snapshot every `interval` and keeps
    the freshest world view for the training loop to poll locally."""

    def __init__(self, client: _CoordinatorClient, replica_id: str,
                 interval: float, world: dict[str, Any]):
        super().__init__(daemon=True, name=f"heartbeat-{replica_id}")
        self.client = client
        self.replica_id = replica_id
        self.interval = interval
        self.status: dict[str, Any] = {"phase": PHASE_RESTORING}
        self.world = world
        self._stop = threading.Event()
        # optional per-beat payload producer: run_worker wires the
        # goodput ledger / registry exposition / trace through this so
        # telemetry stays FRESH while the training thread is blocked
        # for tens of seconds inside a compile or restore (a stale
        # snapshot there would hide exactly the burn the observatory
        # exists to show)
        self.enrich: Callable[[], dict[str, Any]] | None = None

    def update(self, **status) -> None:
        self.status = {**self.status, **status}

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.is_set():
            snap = dict(self.status)
            if self.enrich is not None:
                try:
                    snap.update(self.enrich())
                except Exception as e:  # noqa: BLE001 — same contract
                    log.debug("heartbeat enrich failed: %s", e)
            try:
                w = self.client.heartbeat(self.replica_id, **snap)
                if not w.get("known"):
                    w = self.client.register(self.replica_id, **snap)
                self.world = w
            except Exception as e:  # noqa: BLE001 — transient; keep beating
                log.debug("heartbeat failed: %s", e)
            self._stop.wait(self.interval)


def run_worker(wc: WorkerConfig) -> dict[str, Any]:
    """A trainer replica under the elastic coordinator.

    Replicated execution: each worker computes the full global step on
    its own (virtual) device set, with the mesh's data axis sized to
    the live world — the single-process stand-in for one slice of a
    multi-host data-parallel gang, faithful to the resize semantics
    (the data axis IS the replica count ZeRO partitions over). The
    chief (lowest live id) alone writes checkpoints; every generation
    bump triggers restart-from-last-committed at the new world size.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models import llama
    from kubeflow_tpu.train.checkpoint import (
        CheckpointConfig, Checkpointer,
    )

    cfg = llama.LLAMA_TINY
    client = _CoordinatorClient(wc.coordinator_url)
    loss_f = open(wc.loss_log, "a", buffering=1) if wc.loss_log else None

    # Worker-local observatory (ISSUE 14): a private registry + tracer
    # (shipped to the coordinator on every heartbeat and federated at
    # /elastic/metrics) and the goodput ledger that books every second
    # of this incarnation's life into an exclusive cause.
    wreg = Registry()
    tracer = obs.Tracer()
    ledger = GoodputLedger()
    bind_ledger_metrics(wreg, ledger)

    def log_loss(step: int, loss: float, generation: int) -> None:
        if loss_f is not None:
            loss_f.write(json.dumps({
                "replica": wc.replica_id, "step": step, "loss": loss,
                "generation": generation}) + "\n")

    world = client.register(wc.replica_id, phase=PHASE_RESTORING)
    hb = _Heartbeater(client, wc.replica_id, wc.heartbeat_s, world)

    def _enrich() -> dict[str, Any]:
        # evaluated by the heartbeat THREAD each beat, so the numbers
        # keep moving while the training thread is pinned inside a
        # compile/restore — exactly when the coordinator's burn rates
        # need to see the overhead accumulating
        payload = tracer.chrome_trace()
        payload["traceEvents"] = (list(payload["traceEvents"])
                                  + ledger.counter_events(prefix="train"))
        return {"goodput": ledger.snapshot(), "metrics": wreg.render(),
                "trace": payload}

    hb.enrich = _enrich
    hb.start()
    deadline = time.monotonic() + wc.join_timeout_s
    while not hb.world.get("ready"):
        if time.monotonic() > deadline:
            hb.stop()
            raise TimeoutError(
                f"world never reached min_replicas="
                f"{hb.world.get('min_replicas')}: {hb.world}")
        time.sleep(wc.heartbeat_s)
    world = hb.world

    generation = world["generation"]
    restores = 0
    corrupt_restores = 0
    saves = 0
    published_steps: set = set()  # committed steps announced to the fleet
    trainer = ckpt = state = None
    last_loss = float("nan")
    last_saved = -1

    def rebuild(world_size: int):
        nonlocal trainer, ckpt, state, restores, last_saved
        last_saved = -1
        if ckpt is not None:
            ckpt.close()
        with ledger.book("compile"):
            trainer = _build_trainer(world_size, cfg,
                                     registry=wreg, tracer=tracer)
        ckpt = Checkpointer(
            CheckpointConfig(
                wc.ckpt_dir, save_interval_steps=wc.save_every,
                enable_async=True, install_crash_handlers=True),
            trainer,
            run_metadata={"replica": wc.replica_id},
            registry=wreg,
        )
        with ledger.book("checkpoint_restore"):
            state = ckpt.restore_or_init(jax.random.key(wc.seed))
        # any step at or below the pre-crash high-water mark is now a
        # re-run: the ledger books it to `replay`, not `productive`
        ledger.note_restore(int(jax.device_get(state.step)))
        restores += 1

    try:
        rebuild(world["world_size"])
    except Exception:
        corrupt_restores += 1
        hb.stop()
        raise
    log.info("worker %s joined generation %d at world %d, step %d",
             wc.replica_id, generation, world["world_size"],
             int(jax.device_get(state.step)))

    def others_behind(world, my_step: int, lag: int = 2) -> bool:
        """Soft lockstep: don't run more than `lag` steps ahead of the
        slowest LIVE member (a restoring survivor re-winds to the last
        committed step; the gang waits for it exactly like a real
        collective would)."""
        steps = [s for rid, s in world.get("steps", {}).items()
                 if rid != wc.replica_id and s is not None]
        return bool(steps) and min(steps) < my_step - lag

    while True:
        step = int(jax.device_get(state.step))
        if step >= wc.total_steps:
            break
        hb.update(step=step, loss=last_loss, phase=PHASE_STEP,
                  generation=generation)
        world = hb.world
        if world["generation"] == generation and \
                others_behind(world, step):
            with ledger.book("stall"):
                time.sleep(wc.heartbeat_s)
            continue
        # `ready` gated only initial formation: a world that shrank
        # BELOW min_replicas still continues (that is the point of
        # elasticity) as long as anyone is left.
        if world["generation"] != generation and world["world_size"] >= 1:
            generation = world["generation"]
            log.warning(
                "worker %s: generation %d, world -> %s; restarting "
                "from last committed checkpoint at %d replicas",
                wc.replica_id, generation, world["members"],
                world["world_size"])
            hb.update(phase=PHASE_RESTORING, generation=generation)
            try:
                rebuild(world["world_size"])
            except Exception:
                corrupt_restores += 1
                hb.stop()
                raise
            continue
        toks, tgts = _deterministic_batch(
            cfg.vocab_size, wc.batch, wc.seq, wc.seed, step)
        # the first call on a fresh Trainer blocks through
        # trace+compile — its wall is booked to `compile`, not to the
        # productive/replay causes (it is overwhelmingly XLA's time)
        compiling = not trainer._stepped
        t_step = time.perf_counter()
        state, loss = trainer.step(
            state, jnp.asarray(toks, jnp.int32),
            jnp.asarray(tgts, jnp.int32))
        # device_get blocks until the step's math is done, so dt is
        # the real step wall, not just the async dispatch
        last_loss = float(jax.device_get(loss))
        new_step = int(jax.device_get(state.step))
        dt_step = time.perf_counter() - t_step
        ledger.note_step(step, dt_step, tokens=wc.batch * wc.seq,
                         flops=trainer.step_flops(wc.batch, wc.seq),
                         compiling=compiling)
        step = new_step
        log_loss(step, last_loss, generation)
        if not compiling:
            # steady-state step wall feeds straggler forensics and the
            # train_step_time SLO; compile walls would drown them
            hb.update(step=step, loss=last_loss, step_seconds=dt_step,
                      generation=generation)
        chief = world.get("chief") == wc.replica_id
        if chief and step % wc.save_every == 0 and step != last_saved:
            hb.update(step=step, loss=last_loss, phase=PHASE_SAVING,
                      generation=generation)
            with ledger.book("checkpoint_save"):
                t_save = time.perf_counter()
                ckpt.save(state, force=True)
                dt_save = time.perf_counter() - t_save
                last_saved = step
                saves += 1
                hb.update(saves=saves, save_seconds=dt_save)
                if wc.publish_url:
                    # publish hook: announce whatever is COMMITTED by
                    # now (async saves trail by one flush — close()
                    # below publishes the final step)
                    _publish_version(wc, ckpt, published_steps)
                if wc.slow_save_s > 0:
                    # Chaos window: the save is dispatched but its
                    # COMMITTED marker cannot appear until the next
                    # save/wait — a SIGKILL in here is a mid-save
                    # crash. The sleep books to checkpoint_save: it
                    # widens exactly the window a slow real save would.
                    time.sleep(wc.slow_save_s)
            hb.update(phase=PHASE_STEP)

    final_step = int(jax.device_get(state.step))
    hb.update(step=final_step, loss=last_loss, phase=PHASE_DONE,
              generation=generation)
    world = hb.world
    if world.get("chief") == wc.replica_id and final_step != last_saved:
        with ledger.book("checkpoint_save"):
            ckpt.save(state, force=True)
    with ledger.book("checkpoint_save"):
        ckpt.close()  # drains async saves + writes COMMITTED markers
    if world.get("chief") == wc.replica_id and wc.publish_url:
        # the final save is durable now: publish it
        _publish_version(wc, ckpt, published_steps)
    # Drain barrier: keep heartbeating until every live member reports
    # done — vanishing the moment WE finish would read as a death to a
    # straggler (soft lockstep keeps the skew to a couple of steps, so
    # this is brief).
    drain_deadline = time.monotonic() + wc.join_timeout_s
    while time.monotonic() < drain_deadline:
        world = hb.world
        steps = world.get("steps", {})
        if all(s is not None and s >= wc.total_steps
               for s in steps.values()):
            break
        time.sleep(wc.heartbeat_s)
    hb.stop()
    result = {
        "replica": wc.replica_id,
        "final_step": final_step,
        "final_loss": last_loss,
        "generation": generation,
        "restores": restores,
        "corrupt_restores": corrupt_restores,
        "world_size": world["world_size"],
        "published": len(published_steps),
        # per-incarnation goodput book: the chaos harness reads these
        # RESULT lines for its per-arm summary table (the processes are
        # gone by the time the table prints)
        "goodput": ledger.snapshot(),
    }
    if loss_f is not None:
        loss_f.close()
    return result


# -- CLI -----------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="elastic trainer fleet: coordinator / worker roles")
    parser.add_argument("role", choices=("coordinator", "worker"))
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator listen port")
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument("--degraded-after-s", type=float, default=1.0)
    parser.add_argument("--dead-after-s", type=float, default=2.0)
    parser.add_argument("--coordinator", default="",
                        help="worker: coordinator base URL")
    parser.add_argument("--replica-id", default="trainer-0")
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--steps", type=int, default=16)
    parser.add_argument("--save-every", type=int, default=4)
    parser.add_argument("--batch", type=int, default=12)
    parser.add_argument("--seq", type=int, default=16)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--slow-save-s", type=float, default=0.0)
    parser.add_argument("--loss-log", default="")
    parser.add_argument("--publish-url", default="",
                        help="fleet router base URL: the chief "
                             "publishes each COMMITTED checkpoint to "
                             "POST /fleet/versions (ISSUE 18)")
    parser.add_argument("--publish-model", default="llama-tiny",
                        help="served model name the published "
                             "versions target")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    if args.role == "coordinator":
        from aiohttp import web

        coord = ElasticCoordinator(
            min_replicas=args.min_replicas,
            degraded_after_s=args.degraded_after_s,
            dead_after_s=args.dead_after_s,
        )
        web.run_app(create_coordinator_app(coord), port=args.port,
                    print=None)
        return 0
    if not args.coordinator or not args.ckpt_dir:
        parser.error("worker needs --coordinator and --ckpt-dir")
    result = run_worker(WorkerConfig(
        coordinator_url=args.coordinator,
        replica_id=args.replica_id,
        ckpt_dir=args.ckpt_dir,
        total_steps=args.steps,
        save_every=args.save_every,
        batch=args.batch,
        seq=args.seq,
        seed=args.seed,
        slow_save_s=args.slow_save_s,
        loss_log=args.loss_log,
        publish_url=args.publish_url,
        publish_model=args.publish_model,
    ))
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
