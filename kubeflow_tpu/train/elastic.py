"""Elastic fault-tolerant trainer fleet: membership, resize, restart.

The serving fleet already knows how to keep a replica set alive
(fleet.registry: heartbeats, staleness sweep, dead detection). This
module points that same machinery at TRAINER replicas and closes the
loop the paper's trainer story needs: when a trainer dies mid-run, the
surviving replicas restart from the last COMMITTED checkpoint at the
new replica count — resize-on-restore (train.checkpoint) re-partitions
the ZeRO-sharded optimizer state over the smaller (or larger) data
axis, and training continues with identical global math.

Roles:
  * `ElasticCoordinator` — wraps a ReplicaRegistry; trainers register/
    heartbeat with (step, loss, phase); the coordinator decides the
    surviving world and stamps it with a monotonically increasing
    `generation`. Any membership change bumps the generation; losing a
    previously-live member also counts a restart (the survivors will
    restart from checkpoint). Exposes `train_replicas{state}`,
    `train_restarts_total` and `train_generation` on its registry.
  * `create_coordinator_app` — the aiohttp surface (register/heartbeat/
    world + /metrics) the worker subprocesses and the chaos harness
    talk to.
  * `run_worker` / `python -m kubeflow_tpu.train.elastic worker` — a
    trainer replica: replicated execution (every worker computes the
    full global step; the mesh's data axis tracks the live world size,
    which is what ZeRO partitions over), chief-only checkpoint writes,
    and in-process restart-from-checkpoint when the generation moves.
  * `resize_state` — live cross-mesh resize without a disk round trip:
    gather under the old trainer's mesh, shard under the new one
    (parallel.sharding.make_shard_and_gather_fns).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import threading
import time
from typing import Any, Callable, Mapping

from kubeflow_tpu import obs
from kubeflow_tpu.controlplane.metrics import Counter, Gauge
from kubeflow_tpu.fleet import registry as fleet_registry
from kubeflow_tpu.fleet.registry import STATES, ReplicaRegistry

log = logging.getLogger(__name__)

LIVE_STATES = (fleet_registry.READY, fleet_registry.DEGRADED)

# Heartbeat phases a trainer replica reports. "saving" matters to the
# chaos harness: it is the window in which a SIGKILL lands mid-
# checkpoint-save.
PHASE_STEP = "step"
PHASE_SAVING = "saving"
PHASE_RESTORING = "restoring"
PHASE_DONE = "done"


class ElasticCoordinator:
    """Decides the surviving trainer world from heartbeats.

    Reuses ReplicaRegistry's staleness machinery verbatim; what it adds
    is trainer-shaped stats (float loss, monotonic step, phase — the
    registry's int-stat schema is serving-specific) and the generation/
    restart bookkeeping the workers key their restarts off.
    """

    def __init__(self, *, min_replicas: int = 1,
                 degraded_after_s: float = 6.0,
                 dead_after_s: float = 20.0,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        self.min_replicas = int(min_replicas)
        self._registry = ReplicaRegistry(
            degraded_after_s=degraded_after_s,
            dead_after_s=dead_after_s,
            clock=clock,
        )
        self._lock = threading.Lock()
        self._stats: dict[str, dict[str, Any]] = {}
        self._members: tuple[str, ...] = ()
        self._generation = 0
        self.registry = registry if registry is not None \
            else obs.default_registry()
        self.replicas_gauge = self.registry.get("train_replicas")
        if self.replicas_gauge is None:
            self.replicas_gauge = Gauge(
                "train_replicas",
                "Trainer replicas by health state (heartbeat-driven; "
                "dead replicas shrink the next generation's world)",
                self.registry)
        self.generation_gauge = self.registry.get("train_generation")
        if self.generation_gauge is None:
            self.generation_gauge = Gauge(
                "train_generation",
                "Monotonic world generation; bumps on any trainer "
                "membership change", self.registry)
        self.restarts_total = self.registry.get("train_restarts_total")
        if self.restarts_total is None:
            self.restarts_total = Counter(
                "train_restarts_total",
                "Fleet-wide restart-from-checkpoint events (a "
                "previously-live trainer left the world)", self.registry)
        for s in STATES:
            self.replicas_gauge.set(0.0, state=s)
        self.generation_gauge.set(0.0)
        self.restarts_total.inc(0.0)
        # The full train_* metric catalog lives on the coordinator's
        # registry so one /metrics scrape sees every family zero-seeded
        # (ci.obs_check train) even before any checkpoint I/O happened.
        obs.get_or_create_histogram(
            self.registry, "train_checkpoint_save_seconds",
            "checkpoint save wall time (async: dispatch + previous-save "
            "drain, not the device->disk copy itself)").seed()
        obs.get_or_create_histogram(
            self.registry, "train_checkpoint_restore_seconds",
            "checkpoint restore wall time onto the current mesh "
            "(includes cross-replica-count resharding on resize)").seed()

    # -- membership ------------------------------------------------------

    def register(self, replica_id: str, **stats) -> dict[str, Any]:
        with self._lock:
            self._registry.register(
                f"trainer://{replica_id}", replica_id=replica_id,
                models=("trainer",))
            self._stats.setdefault(replica_id, {})
            self._note(replica_id, stats)
            self._recompute()
            return self._world_locked()

    def heartbeat(self, replica_id: str, **stats) -> bool:
        """Refresh liveness + trainer stats. False for an unknown id —
        the worker must re-register (coordinator restarted)."""
        with self._lock:
            known = self._registry.heartbeat(replica_id)
            if known:
                self._note(replica_id, stats)
            self._recompute()
            return known

    def _note(self, replica_id: str, stats: Mapping[str, Any]) -> None:
        slot = self._stats.setdefault(replica_id, {})
        for key in ("step", "loss", "phase", "generation"):
            if stats.get(key) is not None:
                slot[key] = stats[key]

    def sweep(self) -> None:
        with self._lock:
            self._recompute()

    def _recompute(self) -> None:
        self._registry.sweep()
        live = tuple(sorted(
            rep.id for rep in self._registry.replicas()
            if rep.state in LIVE_STATES))
        if live != self._members:
            lost = set(self._members) - set(live)
            self._generation += 1
            if lost:
                self.restarts_total.inc()
                log.warning(
                    "trainer world change: lost %s, generation %d -> "
                    "world %s (survivors restart from last committed "
                    "checkpoint)", sorted(lost), self._generation, live)
            else:
                log.info("trainer world grew to %s (generation %d)",
                         live, self._generation)
            self._members = live
        for state, n in self._registry.counts().items():
            self.replicas_gauge.set(float(n), state=state)
        self.generation_gauge.set(float(self._generation))

    # -- world view ------------------------------------------------------

    def _world_locked(self, include_stats: bool = False) -> dict[str, Any]:
        world: dict[str, Any] = {
            "generation": self._generation,
            "members": list(self._members),
            "world_size": len(self._members),
            "min_replicas": self.min_replicas,
            "ready": len(self._members) >= self.min_replicas,
            "chief": self._members[0] if self._members else None,
            # per-member progress rides on every response: workers use
            # it for soft lockstep (never run ahead of the slowest live
            # member by more than a couple of steps)
            "steps": {
                rid: self._stats.get(rid, {}).get("step")
                for rid in self._members
            },
            "phases": {
                rid: self._stats.get(rid, {}).get("phase")
                for rid in self._members
            },
        }
        if include_stats:
            world["replicas"] = {
                rid: dict(self._stats.get(rid, {}))
                for rid in self._members
            }
        return world

    def world(self, include_stats: bool = False) -> dict[str, Any]:
        with self._lock:
            self._recompute()
            return self._world_locked(include_stats)


def create_coordinator_app(coord: ElasticCoordinator):
    """The aiohttp surface workers and the chaos harness talk to."""
    from aiohttp import web

    from kubeflow_tpu.obs import endpoints as obs_endpoints

    app = web.Application()

    async def register(request):
        body = await request.json()
        world = coord.register(
            str(body["replica_id"]),
            step=body.get("step"), loss=body.get("loss"),
            phase=body.get("phase"), generation=body.get("generation"))
        return web.json_response(world)

    async def heartbeat(request):
        body = await request.json()
        known = coord.heartbeat(
            str(body["replica_id"]),
            step=body.get("step"), loss=body.get("loss"),
            phase=body.get("phase"), generation=body.get("generation"))
        world = coord.world()
        world["known"] = known
        return web.json_response(world)

    async def world(request):
        return web.json_response(coord.world(include_stats=True))

    app.router.add_post("/elastic/register", register)
    app.router.add_post("/elastic/heartbeat", heartbeat)
    app.router.add_get("/elastic/world", world)
    obs_endpoints.mount_observability(
        app, registry=coord.registry, tracer=obs.DEFAULT_TRACER)
    return app


# -- live cross-mesh resize ---------------------------------------------


def resize_state(state, to_trainer):
    """Re-partition a TrainState onto `to_trainer`'s mesh (e.g. a
    different virtual-replica count) without a checkpoint round trip:
    gather every leaf to host under the old mesh, then place it under
    the new trainer's shardings. The two meshes never meet in one jit.
    """
    import jax

    from kubeflow_tpu.parallel import sharding as sharding_lib

    host = jax.tree.map(jax.device_get, state)
    shard_fns, _ = sharding_lib.make_shard_and_gather_fns(
        to_trainer.state_shardings)
    return jax.tree.map(lambda fn, leaf: fn(leaf), shard_fns, host)


# -- worker --------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WorkerConfig:
    coordinator_url: str
    replica_id: str
    ckpt_dir: str
    total_steps: int = 16
    save_every: int = 4
    # 12 divides by every world size up to 4 (and 6): the global batch
    # must shard over the data axis at EVERY size the world may shrink
    # or grow to, or a resize would change the global math.
    batch: int = 12
    seq: int = 16
    seed: int = 0
    heartbeat_s: float = 0.05
    # Chaos knob: sleep this long after dispatching a checkpoint save,
    # BEFORE the COMMITTED marker can be written — widens the window in
    # which a SIGKILL leaves an uncommitted step dir on disk.
    slow_save_s: float = 0.0
    loss_log: str = ""
    join_timeout_s: float = 60.0


class _CoordinatorClient:
    """Tiny sync JSON client (urllib; workers have no aiohttp loop)."""

    def __init__(self, base_url: str):
        self.base = base_url.rstrip("/")

    def _post(self, path: str, body: dict) -> dict:
        import urllib.request

        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read().decode())

    def register(self, replica_id: str, **stats) -> dict:
        return self._post("/elastic/register",
                          {"replica_id": replica_id, **stats})

    def heartbeat(self, replica_id: str, **stats) -> dict:
        return self._post("/elastic/heartbeat",
                          {"replica_id": replica_id, **stats})


def _deterministic_batch(cfg_vocab: int, batch: int, seq: int, seed: int,
                         step: int):
    """The data stream is a pure function of (seed, step) so every
    replica — and every post-restart incarnation at any world size —
    sees the IDENTICAL global batch. That is what makes loss-curve
    parity across elastic resizes a hard assertion instead of a vibe."""
    import numpy as np

    rng = np.random.default_rng((seed + 1) * 1_000_003 + step)
    toks = rng.integers(0, cfg_vocab, (batch, seq))
    tgts = rng.integers(0, cfg_vocab, (batch, seq))
    return toks, tgts


def _build_trainer(world_size: int, cfg):
    import jax

    from kubeflow_tpu.models import llama
    from kubeflow_tpu.parallel import MeshSpec, create_mesh
    from kubeflow_tpu.train.trainer import TrainConfig, Trainer

    devices = jax.devices()
    if world_size > len(devices):
        raise ValueError(
            f"world size {world_size} exceeds {len(devices)} devices")
    # data axis == world size over a device SUBSET (fsdp=1): any world
    # size up to the device count forms a mesh, so a 3-replica world
    # doesn't need to divide the 8 virtual devices.
    mesh = create_mesh(MeshSpec(data=world_size, fsdp=1, tensor=1),
                       devices=devices[:world_size])
    return Trainer(
        mesh=mesh,
        apply_fn=lambda p, t: llama.apply(p, cfg, t),
        init_fn=lambda k: llama.init(k, cfg),
        logical_axes=llama.param_logical_axes(cfg),
        train_config=TrainConfig(warmup_steps=2, total_steps=1000),
    )


class _Heartbeater(threading.Thread):
    """Off-thread heartbeat loop: the training thread blocks for tens
    of seconds inside the first (and first-post-resize) jit compile,
    which must NOT read as death to the coordinator. The thread posts
    the latest (step, loss, phase) snapshot every `interval` and keeps
    the freshest world view for the training loop to poll locally."""

    def __init__(self, client: _CoordinatorClient, replica_id: str,
                 interval: float, world: dict[str, Any]):
        super().__init__(daemon=True, name=f"heartbeat-{replica_id}")
        self.client = client
        self.replica_id = replica_id
        self.interval = interval
        self.status: dict[str, Any] = {"phase": PHASE_RESTORING}
        self.world = world
        self._stop = threading.Event()

    def update(self, **status) -> None:
        self.status = {**self.status, **status}

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        while not self._stop.is_set():
            snap = dict(self.status)
            try:
                w = self.client.heartbeat(self.replica_id, **snap)
                if not w.get("known"):
                    w = self.client.register(self.replica_id, **snap)
                self.world = w
            except Exception as e:  # noqa: BLE001 — transient; keep beating
                log.debug("heartbeat failed: %s", e)
            self._stop.wait(self.interval)


def run_worker(wc: WorkerConfig) -> dict[str, Any]:
    """A trainer replica under the elastic coordinator.

    Replicated execution: each worker computes the full global step on
    its own (virtual) device set, with the mesh's data axis sized to
    the live world — the single-process stand-in for one slice of a
    multi-host data-parallel gang, faithful to the resize semantics
    (the data axis IS the replica count ZeRO partitions over). The
    chief (lowest live id) alone writes checkpoints; every generation
    bump triggers restart-from-last-committed at the new world size.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models import llama
    from kubeflow_tpu.train.checkpoint import (
        CheckpointConfig, Checkpointer,
    )

    cfg = llama.LLAMA_TINY
    client = _CoordinatorClient(wc.coordinator_url)
    loss_f = open(wc.loss_log, "a", buffering=1) if wc.loss_log else None

    def log_loss(step: int, loss: float, generation: int) -> None:
        if loss_f is not None:
            loss_f.write(json.dumps({
                "replica": wc.replica_id, "step": step, "loss": loss,
                "generation": generation}) + "\n")

    world = client.register(wc.replica_id, phase=PHASE_RESTORING)
    hb = _Heartbeater(client, wc.replica_id, wc.heartbeat_s, world)
    hb.start()
    deadline = time.monotonic() + wc.join_timeout_s
    while not hb.world.get("ready"):
        if time.monotonic() > deadline:
            hb.stop()
            raise TimeoutError(
                f"world never reached min_replicas="
                f"{hb.world.get('min_replicas')}: {hb.world}")
        time.sleep(wc.heartbeat_s)
    world = hb.world

    generation = world["generation"]
    restores = 0
    corrupt_restores = 0
    trainer = ckpt = state = None
    last_loss = float("nan")
    last_saved = -1

    def rebuild(world_size: int):
        nonlocal trainer, ckpt, state, restores, last_saved
        last_saved = -1
        if ckpt is not None:
            ckpt.close()
        trainer = _build_trainer(world_size, cfg)
        ckpt = Checkpointer(
            CheckpointConfig(
                wc.ckpt_dir, save_interval_steps=wc.save_every,
                enable_async=True, install_crash_handlers=True),
            trainer,
            run_metadata={"replica": wc.replica_id},
        )
        state = ckpt.restore_or_init(jax.random.key(wc.seed))
        restores += 1

    try:
        rebuild(world["world_size"])
    except Exception:
        corrupt_restores += 1
        hb.stop()
        raise
    log.info("worker %s joined generation %d at world %d, step %d",
             wc.replica_id, generation, world["world_size"],
             int(jax.device_get(state.step)))

    def others_behind(world, my_step: int, lag: int = 2) -> bool:
        """Soft lockstep: don't run more than `lag` steps ahead of the
        slowest LIVE member (a restoring survivor re-winds to the last
        committed step; the gang waits for it exactly like a real
        collective would)."""
        steps = [s for rid, s in world.get("steps", {}).items()
                 if rid != wc.replica_id and s is not None]
        return bool(steps) and min(steps) < my_step - lag

    while True:
        step = int(jax.device_get(state.step))
        if step >= wc.total_steps:
            break
        hb.update(step=step, loss=last_loss, phase=PHASE_STEP,
                  generation=generation)
        world = hb.world
        if world["generation"] == generation and \
                others_behind(world, step):
            time.sleep(wc.heartbeat_s)
            continue
        # `ready` gated only initial formation: a world that shrank
        # BELOW min_replicas still continues (that is the point of
        # elasticity) as long as anyone is left.
        if world["generation"] != generation and world["world_size"] >= 1:
            generation = world["generation"]
            log.warning(
                "worker %s: generation %d, world -> %s; restarting "
                "from last committed checkpoint at %d replicas",
                wc.replica_id, generation, world["members"],
                world["world_size"])
            hb.update(phase=PHASE_RESTORING, generation=generation)
            try:
                rebuild(world["world_size"])
            except Exception:
                corrupt_restores += 1
                hb.stop()
                raise
            continue
        toks, tgts = _deterministic_batch(
            cfg.vocab_size, wc.batch, wc.seq, wc.seed, step)
        state, loss = trainer.step(
            state, jnp.asarray(toks, jnp.int32),
            jnp.asarray(tgts, jnp.int32))
        last_loss = float(jax.device_get(loss))
        step = int(jax.device_get(state.step))
        log_loss(step, last_loss, generation)
        chief = world.get("chief") == wc.replica_id
        if chief and step % wc.save_every == 0 and step != last_saved:
            hb.update(step=step, loss=last_loss, phase=PHASE_SAVING,
                      generation=generation)
            ckpt.save(state, force=True)
            last_saved = step
            if wc.slow_save_s > 0:
                # Chaos window: the save is dispatched but its
                # COMMITTED marker cannot appear until the next
                # save/wait — a SIGKILL in here is a mid-save crash.
                time.sleep(wc.slow_save_s)
            hb.update(phase=PHASE_STEP)

    final_step = int(jax.device_get(state.step))
    hb.update(step=final_step, loss=last_loss, phase=PHASE_DONE,
              generation=generation)
    world = hb.world
    if world.get("chief") == wc.replica_id and final_step != last_saved:
        ckpt.save(state, force=True)
    ckpt.close()  # drains async saves + writes COMMITTED markers
    # Drain barrier: keep heartbeating until every live member reports
    # done — vanishing the moment WE finish would read as a death to a
    # straggler (soft lockstep keeps the skew to a couple of steps, so
    # this is brief).
    drain_deadline = time.monotonic() + wc.join_timeout_s
    while time.monotonic() < drain_deadline:
        world = hb.world
        steps = world.get("steps", {})
        if all(s is not None and s >= wc.total_steps
               for s in steps.values()):
            break
        time.sleep(wc.heartbeat_s)
    hb.stop()
    result = {
        "replica": wc.replica_id,
        "final_step": final_step,
        "final_loss": last_loss,
        "generation": generation,
        "restores": restores,
        "corrupt_restores": corrupt_restores,
        "world_size": world["world_size"],
    }
    if loss_f is not None:
        loss_f.close()
    return result


# -- CLI -----------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="elastic trainer fleet: coordinator / worker roles")
    parser.add_argument("role", choices=("coordinator", "worker"))
    parser.add_argument("--port", type=int, default=0,
                        help="coordinator listen port")
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument("--degraded-after-s", type=float, default=1.0)
    parser.add_argument("--dead-after-s", type=float, default=2.0)
    parser.add_argument("--coordinator", default="",
                        help="worker: coordinator base URL")
    parser.add_argument("--replica-id", default="trainer-0")
    parser.add_argument("--ckpt-dir", default="")
    parser.add_argument("--steps", type=int, default=16)
    parser.add_argument("--save-every", type=int, default=4)
    parser.add_argument("--batch", type=int, default=12)
    parser.add_argument("--seq", type=int, default=16)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--slow-save-s", type=float, default=0.0)
    parser.add_argument("--loss-log", default="")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    if args.role == "coordinator":
        from aiohttp import web

        coord = ElasticCoordinator(
            min_replicas=args.min_replicas,
            degraded_after_s=args.degraded_after_s,
            dead_after_s=args.dead_after_s,
        )
        web.run_app(create_coordinator_app(coord), port=args.port,
                    print=None)
        return 0
    if not args.coordinator or not args.ckpt_dir:
        parser.error("worker needs --coordinator and --ckpt-dir")
    result = run_worker(WorkerConfig(
        coordinator_url=args.coordinator,
        replica_id=args.replica_id,
        ckpt_dir=args.ckpt_dir,
        total_steps=args.steps,
        save_every=args.save_every,
        batch=args.batch,
        seq=args.seq,
        seed=args.seed,
        slow_save_s=args.slow_save_s,
        loss_log=args.loss_log,
    ))
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
