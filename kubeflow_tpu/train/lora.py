"""LoRA: low-rank adapters for fine-tuning on a fraction of the HBM.

Full fine-tuning of an 8B model carries 2x-params Adam moments; LoRA
(Hu et al. 2021) trains W + (alpha/r) A@B with A,B of rank r, so
gradients and moments exist only for the adapters (~0.1% of params).
TPU-first design decisions:

- Adapters are STACKED per layer ([L, in, r] / [L, r, out]) exactly
  like the model's block weights, so the same `lax.scan` layer loop,
  the same sharding-rule machinery, and the same Orbax checkpointing
  apply unchanged.
- Training MERGES W + AB each step instead of threading a second
  matmul through the model: the merge is one einsum per weight that
  XLA schedules once per step, the model code stays untouched, and the
  backward pass through the merge gives exactly dA = W_grad-contracted
  ... B^T etc. for free. The base tree rides under
  `jax.lax.stop_gradient`, so its cotangents are dead code XLA
  eliminates.
- The frozen base lives INSIDE the TrainState ({"base": ..., "lora":
  ...}) rather than as a jit closure constant (an 8B constant would be
  baked into the executable); Trainer's `freeze_labels` gives the base
  group zero updates and EMPTY optimizer state (trainer.make_optimizer)
  — the memory win that makes LoRA LoRA.
- `merge_lora` also serves deployment: fold adapters into plain params
  once, then serve (optionally through serving.quant int8).

Reference parity: none — the reference has no training of any kind
(SURVEY.md §2b); this extends the Trainer the way Katib extends
experiments: fine-tuning is the HPO sweep's inner loop.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# in/out dims of each adaptable block weight, as attributes of the model
# config (llama and gemma share the schema).
_TARGET_DIMS = {
    "wq": ("hidden_size", "q_dim"),
    "wk": ("hidden_size", "kv_dim"),
    "wv": ("hidden_size", "kv_dim"),
    "wo": ("q_dim", "hidden_size"),
    "w_gate": ("hidden_size", "intermediate_size"),
    "w_up": ("hidden_size", "intermediate_size"),
    "w_down": ("intermediate_size", "hidden_size"),
}


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    # Which block weights get adapters. Attention-only is the classic
    # recipe; the default adapts every block matmul.
    targets: tuple[str, ...] = (
        "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

    def __post_init__(self):
        unknown = set(self.targets) - set(_TARGET_DIMS)
        if unknown:
            raise ValueError(f"unknown LoRA targets {sorted(unknown)} "
                             f"(known: {sorted(_TARGET_DIMS)})")
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def init_lora(rng: jax.Array, cfg, lora_cfg: LoraConfig,
              dtype=jnp.float32) -> Params:
    """Adapters: A ~ fan-in-scaled normal, B = 0 (so the merged model
    starts EXACTLY at the base model — step 0 changes nothing)."""
    L = cfg.num_layers
    out: Params = {"blocks": {}}
    keys = jax.random.split(rng, len(lora_cfg.targets))
    for key, name in zip(keys, lora_cfg.targets):
        d_in = getattr(cfg, _TARGET_DIMS[name][0])
        d_out = getattr(cfg, _TARGET_DIMS[name][1])
        out["blocks"][name] = {
            "A": (jax.random.truncated_normal(
                key, -2, 2, (L, d_in, lora_cfg.rank), jnp.float32)
                * (d_in ** -0.5)).astype(dtype),
            "B": jnp.zeros((L, lora_cfg.rank, d_out), dtype),
        }
    return out


def merge_lora(base: Params, adapters: Params,
               lora_cfg: LoraConfig) -> Params:
    """base params with W <- W + (alpha/r) A@B for every adapted weight.
    Works on any llama-schema params tree; result dtype follows W."""
    blocks = dict(base["blocks"])
    for name, ab in adapters["blocks"].items():
        w = blocks[name]
        delta = jnp.einsum(
            "lir,lro->lio",
            ab["A"].astype(jnp.float32), ab["B"].astype(jnp.float32))
        blocks[name] = (w.astype(jnp.float32)
                        + lora_cfg.scaling * delta).astype(w.dtype)
    out = dict(base)
    out["blocks"] = blocks
    return out


def lora_logical_axes(base_axes: Params, lora_cfg: LoraConfig) -> Params:
    """Adapter logical axes mirroring the base weight's: A keeps the
    in-axis sharding, B the out-axis; the rank axis replicates (it is
    tiny). `base_axes` is the model's param_logical_axes tree."""
    out: Params = {"blocks": {}}
    for name in lora_cfg.targets:
        layers_ax, in_ax, out_ax = base_axes["blocks"][name]
        out["blocks"][name] = {
            "A": (layers_ax, in_ax, "lora_rank"),
            "B": (layers_ax, "lora_rank", out_ax),
        }
    return out


def lora_train_tree(base: Params, adapters: Params) -> Params:
    return {"base": base, "lora": adapters}


def lora_freeze_labels(tree: Params) -> Params:
    """Trainer freeze_labels for a lora_train_tree: base frozen (no
    updates, no optimizer state), adapters trained."""
    return {
        "base": jax.tree.map(lambda _: "freeze", tree["base"]),
        "lora": jax.tree.map(lambda _: "train", tree["lora"]),
    }


def lora_loss_fn(model_loss_fn, lora_cfg: LoraConfig):
    """Wrap a `loss(params, tokens, targets, mask)` into one over the
    {"base", "lora"} train tree: merge (base under stop_gradient — its
    cotangents are dead code), then evaluate the model loss."""
    def loss(tree: Params, tokens, targets, mask):
        merged = merge_lora(
            jax.lax.stop_gradient(tree["base"]), tree["lora"], lora_cfg)
        return model_loss_fn(merged, tokens, targets, mask)

    return loss
