"""Worker-second goodput ledger for the elastic trainer fleet.

The elastic fleet (train.elastic) can lose a worker, shrink the world,
and replay from the last COMMITTED checkpoint — but until now nothing
measured what that elasticity COSTS. This module books every wall
second a worker lives into exactly one cause from a closed set, the
same structural-conservation discipline as PR 8's phase-sums == wall
and PR 13's block births - frees == live:

  productive          — step compute that advanced the run past its
                        high-water step (the only seconds that count
                        toward goodput)
  replay              — steps re-run between the last committed
                        checkpoint and the crash point (the direct
                        price of a restart)
  checkpoint_save     — chief-side save dispatch + drain
  checkpoint_restore  — restore-or-init onto the current mesh
  compile             — first-step trace+compile after a (re)build
  stall               — soft-lockstep waits on a slower live member
  idle                — everything else (residual; join barriers,
                        heartbeat sleeps, host gaps)

Conservation invariant (asserted by tests and `ci/obs_check train-obs`):
    sum(seconds over all causes) == wall seconds since the ledger was
    born, and `unattributed == 0` — an overlapped double-booking (a
    bug) surfaces as a positive `unattributed` residual instead of
    silently inflating a cause.

The ledger is metric-free and jax-free (importable in the coordinator,
in workers, and in fake-clock tests); train.elastic binds it to real
counters/gauges on the worker registry, the same wiring idiom as
`CacheLedger.on_free`. MFU and tokens/s derive from the model-FLOPs
estimate in train.trainer (`estimate_step_flops`): MFU needs the
accelerator's peak FLOP/s, which only the deployment knows, so it is
an optional constructor argument and reads 0.0 when absent.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Callable

from kubeflow_tpu import obs
from kubeflow_tpu.obs.cachestats import UNATTRIBUTED

# Closed set of causes a worker-second is booked to. These become the
# `cause` label on `train_goodput_seconds_total`, so the set is CLOSED
# by design (LabelGuard-free by construction).
GOODPUT_CAUSES = ("productive", "replay", "checkpoint_save",
                  "checkpoint_restore", "compile", "stall", "idle")
# The subset that is pure overhead — what the coordinator aggregates
# into `train_replay_seconds_total{cause}` (fleet seconds NOT spent
# advancing the run, by cause).
LOST_CAUSES = ("replay", "checkpoint_save", "checkpoint_restore",
               "compile", "stall", "idle")

_MAX_COUNTER_EVENTS = 2048
_EPS = 1e-6


class GoodputLedger:
    """Books one worker's wall seconds into exclusive causes.

    Usage (train.elastic.run_worker):
        ledger = GoodputLedger()
        with ledger.book("checkpoint_restore"):
            state = ckpt.restore_or_init(...)
        ledger.note_restore(int(state.step))
        ledger.note_step(step, dt, tokens=..., flops=...,
                         compiling=first_call)
        ...
        snap = ledger.snapshot()   # balanced view: booked == wall

    `book` frames may nest; attribution is exclusive (inner time is
    subtracted from the enclosing frame), mirroring PhaseProfiler.
    `snapshot`/`cause_seconds` never mutate: the idle residual (wall
    minus everything explicitly booked, including still-open frames) is
    computed at read time, so the conservation equality holds at EVERY
    scrape, not only at quiescence.
    """

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 peak_flops_per_s: float = 0.0,
                 wall: Callable[[], float] = time.time):
        self._lock = threading.Lock()
        self._clock = clock
        self._wall = wall
        self._t0 = clock()
        self.peak_flops_per_s = float(peak_flops_per_s)
        self.seconds = {c: 0.0 for c in (*GOODPUT_CAUSES, UNATTRIBUTED)}
        # open `book` frames: [cause, start, finished_child_seconds]
        self._frames: list[list] = []
        # replay horizon: steps <= this index already ran in a previous
        # incarnation and are re-runs, not progress
        self._max_step_seen = -1
        self._replay_until = -1
        self.productive_steps = 0
        self.replay_steps = 0
        self.tokens = 0            # tokens from PRODUCTIVE steps only
        self.flops = 0.0           # model FLOPs from productive steps
        self.last_step_seconds = 0.0
        self.restores = 0
        # Chrome "C" counter events: one all-zero seed so the track
        # exists in every merged trace, then one point per booking.
        self._events: deque = deque(maxlen=_MAX_COUNTER_EVENTS)
        self._emit_event()
        # metric bindings; exceptions swallowed (CacheLedger idiom)
        self.on_book: Callable[[str, float], None] | None = None

    # -- write side --------------------------------------------------------

    @contextlib.contextmanager
    def book(self, cause: str):
        """Book the frame's EXCLUSIVE wall time to `cause`."""
        if cause not in self.seconds:
            cause = UNATTRIBUTED
        with self._lock:
            self._frames.append([cause, self._clock(), 0.0])
        try:
            yield
        finally:
            now = self._clock()
            with self._lock:
                _, start, child = self._frames.pop()
                dt = now - start
                own = max(dt - child, 0.0)
                self.seconds[cause] += own
                if self._frames:
                    self._frames[-1][2] += dt
                self._emit_event()
            self._fire(cause, own)

    def note_step(self, step: int, seconds: float, *, tokens: int = 0,
                  flops: float = 0.0, compiling: bool = False) -> None:
        """Book one train-step wall. `step` is the PRE-step index (the
        step being computed); `compiling` attributes a first-call-after-
        rebuild step to `compile` (the wall is overwhelmingly the jit
        trace+compile, not the math)."""
        seconds = max(float(seconds), 0.0)
        with self._lock:
            if compiling:
                cause = "compile"
            elif step <= self._replay_until:
                cause = "replay"
                self.replay_steps += 1
            else:
                cause = "productive"
                self.productive_steps += 1
                self.tokens += int(tokens)
                self.flops += float(flops)
                self.last_step_seconds = seconds
            self.seconds[cause] += seconds
            self._max_step_seen = max(self._max_step_seen, int(step))
            self._emit_event()
        self._fire(cause, seconds)

    def note_restore(self, restored_step: int) -> None:
        """Declare a restore landed at `restored_step`: any step index
        at or below the pre-crash high-water mark is now a re-run."""
        with self._lock:
            self.restores += 1
            if self._max_step_seen > int(restored_step):
                self._replay_until = self._max_step_seen

    # -- read side ---------------------------------------------------------

    def _open_seconds_locked(self, now: float) -> dict[str, float]:
        """Exclusive elapsed of still-open frames: frame i owns the
        span up to the next frame's start (or now), minus its finished
        children — exact because children are strictly nested."""
        out: dict[str, float] = {}
        for i, (cause, start, child) in enumerate(self._frames):
            end = self._frames[i + 1][1] if i + 1 < len(self._frames) \
                else now
            own = max(end - start - child, 0.0)
            out[cause] = out.get(cause, 0.0) + own
        return out

    def _balanced_view(self, now: float) -> tuple[dict[str, float], float]:
        """Balanced per-cause view AT `now`: explicit bookings + open
        frames + the idle residual, guaranteed to sum to the returned
        wall unless bookings overlapped (which books the excess to
        `unattributed` so the breach is visible, not hidden). One clock
        read drives both sides — a second read between the view and the
        wall would break the equality by the microseconds in between."""
        with self._lock:
            view = dict(self.seconds)
            for cause, own in self._open_seconds_locked(now).items():
                view[cause] += own
            wall = now - self._t0
        residual = wall - sum(view.values())
        if residual >= 0.0:
            view["idle"] += residual
        else:
            view[UNATTRIBUTED] += -residual
        return view, wall

    def cause_seconds(self) -> dict[str, float]:
        return self._balanced_view(self._clock())[0]

    def wall_seconds(self) -> float:
        return self._clock() - self._t0

    def snapshot(self) -> dict:
        """Heartbeat / debug payload: cause seconds, conservation
        fields, and the derived rates (goodput fraction, tokens/s, MFU
        when peak FLOP/s is known)."""
        view, wall = self._balanced_view(self._clock())
        booked = sum(view.values())
        productive = view["productive"]
        with self._lock:
            out = {
                "seconds": view,
                "wall_seconds": wall,
                "booked_seconds": booked,
                "productive_steps": self.productive_steps,
                "replay_steps": self.replay_steps,
                "restores": self.restores,
                "tokens": self.tokens,
                "flops": self.flops,
                "last_step_seconds": self.last_step_seconds,
            }
        out["goodput_fraction"] = productive / booked if booked > _EPS \
            else 0.0
        out["tokens_per_second"] = out["tokens"] / productive \
            if productive > _EPS else 0.0
        out["mfu"] = (out["flops"] / productive / self.peak_flops_per_s) \
            if productive > _EPS and self.peak_flops_per_s > 0 else 0.0
        out["conserved"] = (abs(booked - wall) <= max(_EPS, 1e-9 * wall)
                            and view[UNATTRIBUTED] <= _EPS)
        return out

    # -- chrome counter tracks --------------------------------------------

    def _emit_event(self) -> None:
        # caller holds the lock
        self._events.append({
            "name": "goodput_seconds", "ph": "C",
            "ts": round(self._wall() * 1e6, 1), "pid": 1, "tid": 0,
            "args": {c: round(self.seconds[c], 4)
                     for c in GOODPUT_CAUSES},
        })

    def counter_events(self, *, prefix: str = "") -> list[dict]:
        """Chrome "C" events for the merged `/elastic/traces` view
        (cumulative booked seconds per cause over time)."""
        with self._lock:
            evs = [dict(e) for e in self._events]
        if prefix:
            for e in evs:
                e["name"] = f"{prefix}.{e['name']}"
        return evs

    def _fire(self, cause: str, seconds: float) -> None:
        if self.on_book is not None:
            try:
                self.on_book(cause, seconds)
            except Exception:
                pass


# -- shared checkpoint-latency catalog ------------------------------------

_CKPT_SAVE_HELP = ("checkpoint save wall time (async: dispatch + "
                   "previous-save drain, not the device->disk copy "
                   "itself)")
_CKPT_RESTORE_HELP = ("checkpoint restore wall time onto the current "
                      "mesh (includes cross-replica-count resharding "
                      "on resize)")


def goodput_metrics(registry):
    """Get-or-create + zero-seed the worker-side goodput families.

    One definition site for name/help/label sets, used by BOTH the
    worker (whose registry actually observes them) and the coordinator
    (which seeds the same families so a scrape with zero live workers
    still shows the full catalog shape). Returns
    `(seconds_total, wall_gauge, tokens_per_s, replay_steps_total)`.
    """
    from kubeflow_tpu.controlplane.metrics import Counter, Gauge

    seconds = registry.get("train_goodput_seconds_total")
    if seconds is None:
        seconds = Counter(
            "train_goodput_seconds_total",
            "Worker wall seconds booked by exclusive cause "
            "(conservation: sums to train_goodput_wall_seconds; "
            "unattributed stays 0)", registry)
    for c in (*GOODPUT_CAUSES, UNATTRIBUTED):
        seconds.inc(0.0, cause=c)
    wall = registry.get("train_goodput_wall_seconds")
    if wall is None:
        wall = Gauge(
            "train_goodput_wall_seconds",
            "Wall seconds since the worker's goodput ledger was born "
            "(the conservation denominator; federated sum = total "
            "fleet worker-seconds)", registry)
        wall.set(0.0)
    tokens_per_s = registry.get("train_tokens_per_second")
    if tokens_per_s is None:
        tokens_per_s = Gauge(
            "train_tokens_per_second",
            "Productive tokens over productive seconds per worker "
            "(federated sum = aggregate fleet tokens/s — the elastic "
            "scaling acceptance metric)", registry)
        tokens_per_s.set(0.0)
    replay_steps = registry.get("train_replay_steps_total")
    if replay_steps is None:
        replay_steps = Counter(
            "train_replay_steps_total",
            "Steps re-run between the last committed checkpoint and "
            "the crash point", registry)
    replay_steps.inc(0.0)
    return seconds, wall, tokens_per_s, replay_steps


def bind_ledger_metrics(registry, ledger: GoodputLedger):
    """Wire a worker registry to a ledger via a render-time collector:
    every `/metrics` scrape re-syncs the goodput families from a fresh
    balanced snapshot, so the exposition's conservation equality
    (sum over causes == wall gauge) holds at scrape time BY
    construction — the counters are the ledger, not a sampled copy."""
    seconds, wall, tokens_per_s, replay_steps = goodput_metrics(registry)

    def _collect():
        snap = ledger.snapshot()
        for c, v in snap["seconds"].items():
            cur = seconds.value(cause=c)
            if v > cur:
                seconds.inc(v - cur, cause=c)
        wall.set(snap["wall_seconds"])
        tokens_per_s.set(snap["tokens_per_second"])
        cur = replay_steps.value()
        if snap["replay_steps"] > cur:
            replay_steps.inc(snap["replay_steps"] - cur)

    registry.register_collector(_collect)
    return seconds, wall, tokens_per_s, replay_steps


def checkpoint_histograms(registry):
    """THE definition of `train_checkpoint_{save,restore}_seconds`.

    Both the Checkpointer (the observer) and the ElasticCoordinator
    (which zero-seeds the full train catalog on its own registry) used
    to register these independently; one get-or-create site means the
    name/help/bucket definitions cannot drift between them. Returns
    `(save_seconds, restore_seconds)`, both seeded.
    """
    save = obs.get_or_create_histogram(
        registry, "train_checkpoint_save_seconds", _CKPT_SAVE_HELP)
    restore = obs.get_or_create_histogram(
        registry, "train_checkpoint_restore_seconds", _CKPT_RESTORE_HELP)
    save.seed()
    restore.seed()
    return save, restore
