"""Fused decode attention over the KV cache (Pallas TPU kernel).

The decode step's attention is one query token per row against that
row's cache prefix. The XLA path computes masked scores over the FULL
[max_len] cache for every row — correct, but it streams the invalid
tail through HBM every token, and decode MBU is the whole game
(bench.py's roofline). This kernel (VERDICT r04 stretch #9):

- grid = (rows, kv blocks); each row's cursor is SCALAR-PREFETCHED so
  blocks wholly past the cursor are skipped — the BlockSpec index map
  clamps to the last needed block (a repeated index means no new DMA)
  and `pl.when` gates the compute, so HBM traffic tracks the cache
  FILL, not max_len;
- GQA stays at KV resolution in memory (queries reshape to
  [n_kv, group] inside the kernel; the cache never repeats);
- per-cell validity (the engines' left-pad holes) rides in as a mask
  block; causality and sliding windows mask by absolute cell index
  against the prefetched cursor.

Numerics match ops.attention._xla_attention exactly in structure:
fp32 logits, one softmax over the visible set (single-pass here — the
online-softmax merge is algebraically the same sum).

Reference parity: the reference has no attention code (SURVEY.md §2b);
this is the serving-side sibling of flash_attention.py, pinned against
the XLA oracle by tests/test_decode_attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubeflow_tpu.ops.attention import NEG_INF
from kubeflow_tpu.ops.pallas.flash_attention import (
    _interpret_default,
    _pick_block,
)

DEFAULT_BLOCK_K = 256


def _kernel(pos_ref, q_ref, k_ref, v_ref, mask_ref, o_ref,
            acc, m_scr, l_scr, *, scale, window, block_k, nk, n_kv,
            group):
    b_i, ki = pl.program_id(0), pl.program_id(1)
    pos = pos_ref[b_i]

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # Relevance: skip blocks past the cursor AND (with a sliding
    # window) blocks wholly older than the attention band — without
    # the lower bound, a window-1024 model at cursor 32k would stream
    # all 32k cells per token, the exact waste this kernel exists to
    # cut on the causal side.
    relevant = ki * block_k <= pos
    if window is not None:
        relevant &= (ki * block_k + block_k - 1) >= pos - window + 1

    @pl.when(relevant)
    def _compute():
        n_q = n_kv * group
        q = q_ref[0, 0].astype(jnp.float32)           # [n_q, hd]
        k = k_ref[0].astype(jnp.float32)              # [bk, n_kv, hd]
        qg = q.reshape(n_kv, group, -1)
        kt = jnp.swapaxes(k, 0, 1)                    # [n_kv, bk, hd]
        # [n_kv, group, bk]: batch over kv heads — GQA without repeat
        logits = jax.lax.dot_general(
            qg, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        logits = logits.reshape(n_q, block_k)

        idx = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (n_q, block_k), 1)
        visible = (idx <= pos) & mask_ref[0]          # causal & pad holes
        if window is not None:
            visible &= (pos - idx) < window
        logits = jnp.where(visible, logits, NEG_INF)

        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1))
        p = jnp.exp(logits - m_new[:, None])
        # a fully-masked block contributes nothing, not exp(NEG_INF-m)
        p = jnp.where(visible, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            (l_scr[:, 0] * alpha + jnp.sum(p, axis=1))[:, None],
            l_scr.shape)
        v = v_ref[0].astype(jnp.float32)              # [bk, n_kv, hd]
        vg = jnp.swapaxes(v, 0, 1)                    # [n_kv, bk, hd]
        pv = jax.lax.dot_general(
            p.reshape(n_kv, group, block_k), vg,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(n_q, -1)                            # [n_q, hd]
        acc[:] = acc[:] * alpha[:, None] + pv
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / safe_l[:, None]).astype(o_ref.dtype)


def decode_attention(
    q: jnp.ndarray,            # [b, 1, n_q, hd]
    k: jnp.ndarray,            # [b, max_len, n_kv, hd]
    v: jnp.ndarray,            # [b, max_len, n_kv, hd]
    q_positions: jnp.ndarray,  # [b] int32 — each row's cursor
    kv_mask: jnp.ndarray | None = None,  # [b, max_len] bool
    *,
    window: int | None = None,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One-token-per-row attention over each row's cache prefix."""
    if interpret is None:
        interpret = _interpret_default()
    b, sq, n_q, hd = q.shape
    if sq != 1:
        raise ValueError(f"decode_attention is s=1 only, got sq={sq}")
    max_len = k.shape[1]
    n_kv = k.shape[2]
    if n_q % n_kv:
        raise ValueError(f"{n_q} query heads not grouped by {n_kv} kv")
    group = n_q // n_kv
    if kv_mask is None:
        kv_mask = jnp.ones((b, max_len), bool)
    block_k = _pick_block(max_len, block_k)
    nk = max_len // block_k
    positions = q_positions.astype(jnp.int32)

    # Clamped index maps: iterations outside a row's needed block range
    # re-reference a boundary block — consecutive equal indices skip
    # the DMA, which is where the ragged saving comes from. The range
    # is [first block the window can see, cursor block].
    def _clamp(ki, pos):
        hi = pos // block_k
        if window is None:
            return jnp.minimum(ki, hi)
        lo = jnp.maximum((pos - window + 1) // block_k, 0)
        return jnp.clip(ki, lo, hi)

    def kv_map(b_i, ki, pos_ref):
        return (b_i, _clamp(ki, pos_ref[b_i]), 0, 0)

    def mask_map(b_i, ki, pos_ref):
        return (b_i, _clamp(ki, pos_ref[b_i]))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, nk),
        in_specs=[
            pl.BlockSpec((1, 1, n_q, hd),
                         lambda b_i, ki, pos_ref: (b_i, 0, 0, 0)),
            pl.BlockSpec((1, block_k, n_kv, hd), kv_map),
            pl.BlockSpec((1, block_k, n_kv, hd), kv_map),
            pl.BlockSpec((1, block_k), mask_map),
        ],
        out_specs=pl.BlockSpec((1, 1, n_q, hd),
                               lambda b_i, ki, pos_ref: (b_i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_q, hd), jnp.float32),
            pltpu.VMEM((n_q, 128), jnp.float32),
            pltpu.VMEM((n_q, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel, scale=hd**-0.5, window=window, block_k=block_k,
        nk=nk, n_kv=n_kv, group=group,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(positions, q, k, v, kv_mask)
