"""Pallas TPU kernels for the hot ops (flash attention first).

Kernels are written against the TPU memory hierarchy (HBM → VMEM → MXU)
and tested on CPU in interpreter mode, mirroring how the control plane is
tested against the fake-TPU backend.
"""

from kubeflow_tpu.ops.pallas.flash_attention import flash_attention
from kubeflow_tpu.ops.pallas.paged_attention import paged_decode_attention
