"""Flash attention as Pallas TPU kernels (fwd + custom-VJP bwd).

Blockwise attention that never materializes the [s, s] score matrix:
Q blocks stay VMEM-resident while K/V blocks stream through, merging
into an online-softmax accumulator — O(block_q * block_k) VMEM instead
of O(s^2) HBM, with every matmul landing on the MXU in fp32 accumulation.

Backward is the standard two-kernel formulation (saved row logsumexp +
recomputed probabilities):
  - dq kernel:   grid over Q blocks, streaming K/V blocks;
  - dk/dv kernel: grid over K blocks, streaming Q/dO blocks.
GQA is handled by index-mapping each query head onto its KV head inside
the BlockSpecs (KV never repeats in HBM); dk/dv come out at query-head
resolution and are group-summed outside the kernel.

Causal masking is by absolute row/col block index — packed sequences with
position resets must use the XLA path (see ops.attention dispatcher).

Reference parity: the reference has no attention/compute code at all
(SURVEY.md §2b); this is the TPU-native hot-op layer BASELINE.json's
tokens/sec/chip metric exercises.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubeflow_tpu.ops.attention import NEG_INF


def _apply_causal_mask(logits, qi, ki, block_q, block_k, window):
    rows = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    q_pos = qi * block_q + rows
    k_pos = ki * block_k + cols
    mask = q_pos >= k_pos
    if window is not None:
        # sliding window: attend the last `window` positions (self incl.)
        mask &= (q_pos - k_pos) < window
    return jnp.where(mask, logits, NEG_INF)


def _block_relevant(qi, ki, block_q, block_k, window):
    """Trace-time predicate: does (q block, k block) intersect the
    causal band at all? Above-diagonal blocks skip always; with a
    window, blocks entirely OLDER than the band skip too."""
    newest_q = qi * block_q + block_q - 1
    keep = ki * block_k <= newest_q
    if window is not None:
        oldest_q = qi * block_q
        newest_k = ki * block_k + block_k - 1
        keep &= newest_k > oldest_q - window
    return keep

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(s: int, block: int) -> int:
    b = min(block, s)
    while s % b:
        b //= 2
    return max(b, 1)


# ---------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr,
                *, scale, causal, window, block_q, block_k, nk):
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    def compute():
        q = q_ref[0, 0].astype(jnp.float32)          # [bq, hd]
        k = k_ref[0, 0].astype(jnp.float32)          # [bk, hd]
        v = v_ref[0, 0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                     # [bq, bk]
        if causal:
            logits = _apply_causal_mask(logits, qi, ki, block_q, block_k,
                                        window)

        m_prev = m_scr[:, 0]                          # [bq]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1))
        p = jnp.exp(logits - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_scr[:, 0] * alpha + jnp.sum(p, axis=1)
        acc[:] = acc[:] * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    if causal:
        # Skip blocks strictly above the diagonal, and (with a sliding
        # window) blocks entirely older than the attention band.
        @pl.when(_block_relevant(qi, ki, block_q, block_k, window))
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / safe_l[:, None]).astype(o_ref.dtype)
        lse = m_scr[:, 0] + jnp.log(safe_l)
        # lane-replicated rows: TPU blocks need the trailing dims tiled
        # (8, 128), so per-row scalars are stored [s, 128] like the
        # in-tree kernel's l/m residuals.
        lse_ref[0, 0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[2:])


def _fwd(q4, k4, v4, *, causal, window, block_q, block_k, interpret):
    """q4: [b, nq, s, hd]; k4/v4: [b, nkv, s, hd] → (o4, lse[b, nq, s])."""
    b, nq, s, hd = q4.shape
    nkv = k4.shape[1]
    g = nq // nkv
    scale = hd**-0.5
    block_q = _pick_block(s, block_q)
    block_k = _pick_block(s, block_k)
    nqb, nkb = s // block_q, s // block_k

    grid = (b * nq, nqb, nkb)
    q_spec = pl.BlockSpec(
        (1, 1, block_q, hd),
        lambda bh, qi, ki: (bh // nq, bh % nq, qi, 0),
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, hd),
        lambda bh, qi, ki: (bh // nq, (bh % nq) // g, ki, 0),
    )
    o_spec = pl.BlockSpec(
        (1, 1, block_q, hd),
        lambda bh, qi, ki: (bh // nq, bh % nq, qi, 0),
    )
    lse_spec = pl.BlockSpec(
        (1, 1, block_q, 128),
        lambda bh, qi, ki: (bh // nq, bh % nq, qi, 0),
    )

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, window=window,
        block_q=block_q, block_k=block_k, nk=nkb,
    )
    o4, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[o_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct(q4.shape, q4.dtype),
            jax.ShapeDtypeStruct((b, nq, s, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, hd), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q4, k4, v4)
    return o4, lse


# --------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, window, block_q, block_k, nk):
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0]                     # [bq]
        delta = delta_ref[0, 0][:, 0]                 # [bq]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            logits = _apply_causal_mask(logits, qi, ki, block_q, block_k,
                                        window)
        p = jnp.exp(logits - lse[:, None])            # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        dq_acc[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32) * scale

    if causal:
        @pl.when(_block_relevant(qi, ki, block_q, block_k, window))
        def _():
            compute()
    else:
        compute()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, window, block_q, block_k, nq_blocks):
    ki, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def compute():
        q = q_ref[0, 0].astype(jnp.float32)           # [bq, hd]
        k = k_ref[0, 0].astype(jnp.float32)           # [bk, hd]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0]
        delta = delta_ref[0, 0][:, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                     # [bq, bk]
        if causal:
            logits = _apply_causal_mask(logits, qi, ki, block_q, block_k,
                                        window)
        p = jnp.exp(logits - lse[:, None])
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])                # [bq, bk]
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    if causal:
        # Q blocks strictly above the diagonal see none of this K block
        # (and with a window, q blocks entirely newer than the band).
        @pl.when(_block_relevant(qi, ki, block_q, block_k, window))
        def _():
            compute()
    else:
        compute()

    @pl.when(qi == nq_blocks - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(causal, window, block_q, block_k, interpret, res, do4):
    q4, k4, v4, o4, lse = res
    b, nq, s, hd = q4.shape
    nkv = k4.shape[1]
    g = nq // nkv
    scale = hd**-0.5
    block_q = _pick_block(s, block_q)
    block_k = _pick_block(s, block_k)
    nqb, nkb = s // block_q, s // block_k

    delta = jnp.sum(do4.astype(jnp.float32) * o4.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, 128))

    q_spec = pl.BlockSpec(
        (1, 1, block_q, hd), lambda bh, qi, ki: (bh // nq, bh % nq, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, hd),
        lambda bh, qi, ki: (bh // nq, (bh % nq) // g, ki, 0))
    row_spec = pl.BlockSpec(
        (1, 1, block_q, 128),
        lambda bh, qi, ki: (bh // nq, bh % nq, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          window=window, block_q=block_q,
                          block_k=block_k, nk=nkb),
        grid=(b * nq, nqb, nkb),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q4.shape, q4.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, hd), jnp.float32)],
        interpret=interpret,
    )(q4, k4, v4, do4, lse, delta)

    # dk/dv at query-head resolution; kv-head index maps stream the same
    # K/V block to every query head in the group.
    q_spec2 = pl.BlockSpec(
        (1, 1, block_q, hd), lambda bh, ki, qi: (bh // nq, bh % nq, qi, 0))
    kv_spec2 = pl.BlockSpec(
        (1, 1, block_k, hd),
        lambda bh, ki, qi: (bh // nq, (bh % nq) // g, ki, 0))
    row_spec2 = pl.BlockSpec(
        (1, 1, block_q, 128),
        lambda bh, ki, qi: (bh // nq, bh % nq, qi, 0))
    dkv_out_spec = pl.BlockSpec(
        (1, 1, block_k, hd), lambda bh, ki, qi: (bh // nq, bh % nq, ki, 0))

    dk_full, dv_full = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          window=window, block_q=block_q,
                          block_k=block_k, nq_blocks=nqb),
        grid=(b * nq, nkb, nqb),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=[dkv_out_spec, dkv_out_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, nq, s, hd), k4.dtype),
            jax.ShapeDtypeStruct((b, nq, s, hd), v4.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, hd), jnp.float32),
            pltpu.VMEM((block_k, hd), jnp.float32),
        ],
        interpret=interpret,
    )(q4, k4, v4, do4, lse, delta)

    # Group-sum query-head gradients onto their KV head.
    dk = dk_full.reshape(b, nkv, g, s, hd).sum(axis=2).astype(k4.dtype)
    dv = dv_full.reshape(b, nkv, g, s, hd).sum(axis=2).astype(v4.dtype)
    return dq, dk, dv


# -------------------------------------------------------------- public API


# Block-level entry points for ring attention (parallel.ring): the ring
# composes per-KV-shard kernel calls itself — forward merges per-block
# (o, lse) online, backward re-runs these kernels per visiting block
# against the FINAL (o, lse) residuals, which is mathematically the
# whole-sequence flash bwd split along KV blocks (p = exp(logits - LSE)
# and delta = rowsum(do*o_final) are both global quantities).
def flash_block_fwd(q4, k4, v4, *, causal, interpret, window=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """[b, n, s, hd] tensors -> (normalized o4, lse[b, nq, s, 128])."""
    return _fwd(q4, k4, v4, causal=causal, window=window,
                block_q=block_q, block_k=block_k, interpret=interpret)


def flash_block_bwd(res, do4, *, causal, interpret, window=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """res = (q4, k4, v4, o4, lse128) — o4/lse may be the MERGED ring
    totals; returns (dq4, dk4, dv4) with GQA group-summing applied."""
    return _bwd(causal, window, block_q, block_k, interpret, res, do4)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q4, k4, v4, causal, window, block_q, block_k, interpret):
    o4, _ = _fwd(q4, k4, v4, causal=causal, window=window,
                 block_q=block_q, block_k=block_k, interpret=interpret)
    return o4


def _flash_fwd(q4, k4, v4, causal, window, block_q, block_k, interpret):
    o4, lse = _fwd(q4, k4, v4, causal=causal, window=window,
                   block_q=block_q, block_k=block_k, interpret=interpret)
    return o4, (q4, k4, v4, o4, lse)


def _flash_bwd(causal, window, block_q, block_k, interpret, res, do4):
    return _bwd(causal, window, block_q, block_k, interpret, res, do4)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [b, s, n_q, hd]
    k: jnp.ndarray,  # [b, s, n_kv, hd]
    v: jnp.ndarray,
    *,
    causal: bool = True,
    window: int | None = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Flash attention with GQA, differentiable (custom VJP).

    Layout contract matches ops.attention.dot_product_attention:
    [batch, seq, heads, head_dim] in/out. `interpret=None` auto-selects
    interpreter mode off-TPU so the same code path is testable on the
    hermetic CPU backend.
    """
    if interpret is None:
        interpret = _interpret_default()
    if window is not None and not causal:
        raise ValueError("sliding window requires causal attention")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    b, s, n_q, hd = q.shape
    n_kv = k.shape[2]
    if n_q % n_kv:
        raise ValueError(f"n_q={n_q} not a multiple of n_kv={n_kv}")
    if k.shape[1] != s:
        raise ValueError("flash kernel requires equal q/kv sequence lengths")
    q4 = jnp.transpose(q, (0, 2, 1, 3))
    k4 = jnp.transpose(k, (0, 2, 1, 3))
    v4 = jnp.transpose(v, (0, 2, 1, 3))
    o4 = _flash(q4, k4, v4, causal, window, block_q, block_k, interpret)
    return jnp.transpose(o4, (0, 2, 1, 3))
