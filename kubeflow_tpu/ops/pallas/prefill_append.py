"""Fused prefill/append attention over the PAGED KV pool (Pallas TPU).

The chunked-prefill and speculative-verify paths both feed s >= 1 NEW
tokens per row into a paged cache and attend them against everything
written so far (prefix blocks + the new tokens themselves). The XLA
route is scatter-then-gather: write the s new K/V cells through the
block table, then re-read the row's FULL `[blocks_per_slot *
block_size]` window for attention — the new cells make a round trip
through HBM and the dead tail streams through on every chunk. This
kernel fuses the two:

- grid = (rows, blocks_per_slot); each row's APPEND CURSOR (`q_start`),
  valid-token count (`q_lens`) and BLOCK TABLE are scalar-prefetched,
  so the K/V BlockSpec index maps resolve `table[row, j]` before the
  body runs and DMA only live physical blocks (iterations outside
  [window lo, append hi] are clamped — a repeated physical index skips
  the DMA, as in paged_attention.py);
- per visited block the body MERGES the new tokens in-register (a
  one-hot [block_size, s] matmul scatters token t to cell
  `q_start + t`), writes the merged block back to the pool via
  `input_output_aliases` (in place — the pool is never copied), and
  attends all s queries against the merged block with the shared
  online-softmax merge, masking causally by absolute cell index
  (`idx <= q_start + t`);
- every VISITED block is fully rewritten (blocks without new cells are
  rewritten with their own content): Pallas flushes the output buffer
  whenever its index map moves, so a visited-but-unwritten block would
  flush garbage. Unvisited blocks keep their pool content through the
  aliasing. Shared radix-chain blocks are rewritten with identical
  bytes (new cells land only at `idx >= q_start`, past any shared
  prefix), so cross-row revisits are benign; clamped revisits recompute
  the same merged content, so they are idempotent.

Cell index == logical token position is a precondition, as for the
decode kernel (insert-time compaction guarantees it). A second
precondition: each row's WRITE range `[q_start, q_start + q_lens)`
must lie in blocks no other row's table references (exclusively owned
generation-region blocks) — a write into a block another row reads or
writes in the same call races, because each row's input DMA sees the
pre-call pool, not earlier rows' merges. The serving layers satisfy
both by construction (radix sharing covers only the read-only seed
region below every sharer's cursor). Rows with `q_lens == 0` (group
padding) write nothing and produce garbage attention output the
caller discards.

Pinned against the XLA scatter+gather oracle
(`ops.paged_prefill_attention` impl="xla") by
tests/test_prefill_append_kernel.py in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubeflow_tpu.ops.attention import NEG_INF
from kubeflow_tpu.ops.pallas.flash_attention import _interpret_default


def _kernel(qs_ref, ql_ref, tab_ref, q_ref, kn_ref, vn_ref, kp_ref,
            vp_ref, mask_ref, o_ref, ko_ref, vo_ref, acc, m_scr, l_scr,
            *, scale, window, block_size, s, nb, n_kv, group, hd):
    # tab_ref feeds the BlockSpec index maps; the body needs cursors.
    del tab_ref
    b_i, bj = pl.program_id(0), pl.program_id(1)
    start = qs_ref[b_i]
    n_new = ql_ref[b_i]
    n_q = n_kv * group

    @pl.when(bj == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # Live range: the append's last cell bounds above; a sliding window
    # bounds below (blocks wholly older than the OLDEST query's band
    # are invisible to every query — and writes land at idx >= start,
    # always inside the band).
    relevant = bj * block_size <= start + s - 1
    if window is not None:
        relevant &= (bj * block_size + block_size - 1
                     >= start - window + 1)

    @pl.when(relevant)
    def _compute():
        # --- merge the new tokens into this block, in-register -------
        # cell i of logical block bj holds new token t iff its absolute
        # index equals the token's append position (and t is valid).
        idx_i = bj * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (block_size, s), 0)
        t_i = jax.lax.broadcasted_iota(jnp.int32, (block_size, s), 1)
        sel = (idx_i == start + t_i) & (t_i < n_new)     # [bs, s]
        written = jnp.any(sel, axis=1)                   # [bs]
        selv = sel.astype(jnp.float32)
        kn = kn_ref[0].astype(jnp.float32).reshape(s, n_kv * hd)
        vn = vn_ref[0].astype(jnp.float32).reshape(s, n_kv * hd)
        k_scat = jax.lax.dot_general(
            selv, kn, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(block_size, n_kv, hd)
        v_scat = jax.lax.dot_general(
            selv, vn, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(block_size, n_kv, hd)
        k_blk = jnp.where(written[:, None, None], k_scat,
                          kp_ref[0].astype(jnp.float32))
        v_blk = jnp.where(written[:, None, None], v_scat,
                          vp_ref[0].astype(jnp.float32))
        # full-block writeback (cast to pool dtype FIRST, then attend
        # the cast values — semantics are "attend what the pool holds",
        # matching the XLA scatter-then-gather oracle bit for bit when
        # pool dtype narrows)
        ko_ref[0] = k_blk.astype(ko_ref.dtype)
        vo_ref[0] = v_blk.astype(vo_ref.dtype)
        k_att = ko_ref[0].astype(jnp.float32)
        v_att = vo_ref[0].astype(jnp.float32)

        # --- online-softmax attention of all s queries ---------------
        q = q_ref[0].astype(jnp.float32)                 # [s, n_q, hd]
        qg = q.reshape(s, n_kv, group, hd).transpose(1, 0, 2, 3)
        qg = qg.reshape(n_kv, s * group, hd)
        kt = jnp.swapaxes(k_att, 0, 1)                   # [n_kv, bs, hd]
        logits = jax.lax.dot_general(
            qg, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                  # [n_kv, s*group, bs]
        idx = bj * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (s, block_size), 1)
        qpos = start + jax.lax.broadcasted_iota(
            jnp.int32, (s, block_size), 0)
        visible = (idx <= qpos) & mask_ref[0]      # causal & pad holes
        if window is not None:
            visible &= (qpos - idx) < window
        vis = jnp.broadcast_to(
            visible[:, None, :], (s, group, block_size)
        ).reshape(1, s * group, block_size)
        logits = jnp.where(vis, logits, NEG_INF).reshape(
            n_kv * s * group, block_size)
        visf = jnp.broadcast_to(vis, (n_kv, s * group, block_size)
                                ).reshape(n_kv * s * group, block_size)

        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1))
        p = jnp.exp(logits - m_new[:, None])
        p = jnp.where(visf, p, 0.0)  # fully-masked rows contribute 0
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            (l_scr[:, 0] * alpha + jnp.sum(p, axis=1))[:, None],
            l_scr.shape)
        vg = jnp.swapaxes(v_att, 0, 1)                   # [n_kv, bs, hd]
        pv = jax.lax.dot_general(
            p.reshape(n_kv, s * group, block_size), vg,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(n_kv * s * group, hd)
        acc[:] = acc[:] * alpha[:, None] + pv
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)

    @pl.when(bj == nb - 1)
    def _finish():
        l = l_scr[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out = (acc[:] / safe_l[:, None]).reshape(n_kv, s, group, hd)
        o_ref[0] = out.transpose(1, 0, 2, 3).reshape(
            s, n_q, hd).astype(o_ref.dtype)


def paged_prefill_append(
    q: jnp.ndarray,            # [b, s, n_q, hd]
    k_new: jnp.ndarray,        # [b, s, n_kv, hd]
    v_new: jnp.ndarray,        # [b, s, n_kv, hd]
    k_pool: jnp.ndarray,       # [num_blocks, block_size, n_kv, hd]
    v_pool: jnp.ndarray,       # [num_blocks, block_size, n_kv, hd]
    block_table: jnp.ndarray,  # [b, blocks_per_slot] int32 physical ids
    q_start: jnp.ndarray,      # [b] int32 — append cursor per row
    q_lens: jnp.ndarray,       # [b] int32 — valid new tokens per row
    kv_mask: jnp.ndarray | None = None,  # [b, blocks_per_slot*block_size]
    *,
    window: int | None = None,
    interpret: bool | None = None,
):
    """Append s new tokens per row through the block table and attend
    them, in one pass over the live blocks. Returns
    `(out [b, s, n_q, hd], k_pool, v_pool)` with the pools updated IN
    PLACE (input_output_aliases). HBM traffic per row is one
    read+write of `ceil((q_start + s) / block_size)` blocks — the new
    cells never round-trip, and the table's trash tail is never read.
    """
    if interpret is None:
        interpret = _interpret_default()
    b, s, n_q, hd = q.shape
    if k_new.shape != v_new.shape or k_new.shape[:2] != (b, s):
        raise ValueError(
            f"k_new/v_new must be [b={b}, s={s}, n_kv, hd], got "
            f"{k_new.shape} / {v_new.shape}")
    if k_pool.shape != v_pool.shape:
        raise ValueError(
            f"k_pool/v_pool shapes disagree: {k_pool.shape} vs "
            f"{v_pool.shape}")
    num_blocks, block_size, n_kv, hd_kv = k_pool.shape
    if hd_kv != hd:
        raise ValueError(
            f"head dim mismatch: q has {hd}, pool has {hd_kv}")
    if n_q % n_kv:
        raise ValueError(f"{n_q} query heads not grouped by {n_kv} kv")
    group = n_q // n_kv
    if block_table.ndim != 2 or block_table.shape[0] != b:
        raise ValueError(
            f"block_table must be [b={b}, blocks_per_slot], got "
            f"{block_table.shape}")
    nb = block_table.shape[1]
    width = nb * block_size
    if q_start.shape != (b,) or q_lens.shape != (b,):
        raise ValueError(
            f"q_start/q_lens must be [b={b}], got {q_start.shape} / "
            f"{q_lens.shape}")
    if kv_mask is None:
        kv_mask = jnp.ones((b, width), bool)
    if kv_mask.shape != (b, width):
        raise ValueError(
            f"kv_mask must be [b={b}, {width}], got {kv_mask.shape}")
    starts = q_start.astype(jnp.int32)
    lens = q_lens.astype(jnp.int32)
    table = block_table.astype(jnp.int32)

    # Clamped logical block index: the live range is [window lo, append
    # hi]; out-of-range iterations repeat a boundary block (no DMA) and
    # `pl.when` gates the compute — same scheme as paged_attention.py.
    def _clamp(bj, start):
        hi = (start + s - 1) // block_size
        if window is None:
            return jnp.minimum(bj, hi)
        lo = jnp.maximum((start - window + 1) // block_size, 0)
        return jnp.clip(bj, lo, hi)

    def kv_map(b_i, bj, qs_ref, ql_ref, tab_ref):
        return (tab_ref[b_i, _clamp(bj, qs_ref[b_i])], 0, 0, 0)

    def mask_map(b_i, bj, qs_ref, ql_ref, tab_ref):
        return (b_i, _clamp(bj, qs_ref[b_i]))

    def row_map(b_i, bj, qs_ref, ql_ref, tab_ref):
        return (b_i, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, s, n_q, hd), row_map),
            pl.BlockSpec((1, s, n_kv, hd), row_map),
            pl.BlockSpec((1, s, n_kv, hd), row_map),
            pl.BlockSpec((1, block_size, n_kv, hd), kv_map),
            pl.BlockSpec((1, block_size, n_kv, hd), kv_map),
            pl.BlockSpec((1, block_size), mask_map),
        ],
        out_specs=[
            pl.BlockSpec((1, s, n_q, hd), row_map),
            pl.BlockSpec((1, block_size, n_kv, hd), kv_map),
            pl.BlockSpec((1, block_size, n_kv, hd), kv_map),
        ],
        scratch_shapes=[
            pltpu.VMEM((s * n_q, hd), jnp.float32),
            pltpu.VMEM((s * n_q, 128), jnp.float32),
            pltpu.VMEM((s * n_q, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel, scale=hd**-0.5, window=window, block_size=block_size,
        s=s, nb=nb, n_kv=n_kv, group=group, hd=hd,
    )
    # operand order: 3 prefetch scalars, then q, k_new, v_new, k_pool,
    # v_pool, kv_mask — the pools (operands 6/7) alias outputs 1/2.
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
            jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
        ],
        input_output_aliases={6: 1, 7: 2},
        interpret=interpret,
    )(starts, lens, table, q, k_new, v_new, k_pool, v_pool, kv_mask)
