"""Fused decode attention over the PAGED KV pool (Pallas TPU kernel).

PR 2 moved the continuous engine's KV cache into a shared block pool
(`serving/paged.py`): each slot owns a block table of physical block
ids and a cursor. `ops.paged_attention`'s XLA path gathers every row's
FULL `[blocks_per_slot * block_size]` window through the table before
attending — correct, but it streams the dead tail (and the trash-block
padding) through HBM on every decode step, and decode MBU is the
roofline that matters (bench.py). This kernel walks the table
in-kernel instead:

- grid = (rows, blocks_per_slot); each row's CURSOR and BLOCK TABLE
  are scalar-prefetched, so the K/V BlockSpec index map can resolve
  `table[row, j]` before the body runs and DMA only that physical
  block from the pool;
- iterations past the cursor block (and, with a sliding window, before
  the window's first block) are CLAMPED to the boundary — a repeated
  physical index means no new DMA, so HBM traffic tracks the cache
  FILL, not `blocks_per_slot * block_size` — and `pl.when` gates the
  compute;
- GQA stays at KV resolution (queries reshape to [n_kv, group] inside
  the kernel; the pool never repeats heads);
- per-block partials merge with the same online softmax as
  flash_attention.py / decode_attention.py; per-cell validity (left-pad
  holes) rides in as a mask block indexed by LOGICAL block, causality
  masks by absolute cell index against the prefetched cursor.

Cell index == logical token position is a precondition (the pool's
insert-time compaction guarantees it — see serving/paged.py); callers
with rotated/packed layouts must use the XLA gather path, which masks
by the actual position tensors.

The trash-block-0 convention costs nothing here: clamping confines j
to live blocks, so the table's trash tail is never even read.

Pinned against the XLA gather oracle (`ops.paged_attention`
impl="xla") by tests/test_paged_attention_kernel.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kubeflow_tpu.ops.attention import NEG_INF
from kubeflow_tpu.ops.pallas.flash_attention import _interpret_default


def _kernel(pos_ref, tab_ref, q_ref, k_ref, v_ref, mask_ref, o_ref,
            acc, m_scr, l_scr, *, scale, window, block_size, nb, n_kv,
            group):
    # tab_ref is consumed by the BlockSpec index maps (that's the whole
    # point); the body only needs the cursor.
    del tab_ref
    b_i, bj = pl.program_id(0), pl.program_id(1)
    pos = pos_ref[b_i]

    @pl.when(bj == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # Relevance mirrors decode_attention: skip logical blocks past the
    # cursor AND (with a sliding window) blocks wholly older than the
    # attention band.
    relevant = bj * block_size <= pos
    if window is not None:
        relevant &= (bj * block_size + block_size - 1) >= pos - window + 1

    @pl.when(relevant)
    def _compute():
        n_q = n_kv * group
        q = q_ref[0, 0].astype(jnp.float32)           # [n_q, hd]
        k = k_ref[0].astype(jnp.float32)              # [bs, n_kv, hd]
        qg = q.reshape(n_kv, group, -1)
        kt = jnp.swapaxes(k, 0, 1)                    # [n_kv, bs, hd]
        # [n_kv, group, bs]: batch over kv heads — GQA without repeat
        logits = jax.lax.dot_general(
            qg, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
        logits = logits.reshape(n_q, block_size)

        # Logical cell index == token position (pool compaction).
        idx = bj * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (n_q, block_size), 1)
        visible = (idx <= pos) & mask_ref[0]          # causal & pad holes
        if window is not None:
            visible &= (pos - idx) < window
        logits = jnp.where(visible, logits, NEG_INF)

        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1))
        p = jnp.exp(logits - m_new[:, None])
        # a fully-masked block contributes nothing, not exp(NEG_INF-m)
        p = jnp.where(visible, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            (l_scr[:, 0] * alpha + jnp.sum(p, axis=1))[:, None],
            l_scr.shape)
        v = v_ref[0].astype(jnp.float32)              # [bs, n_kv, hd]
        vg = jnp.swapaxes(v, 0, 1)                    # [n_kv, bs, hd]
        pv = jax.lax.dot_general(
            p.reshape(n_kv, group, block_size), vg,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(n_q, -1)                            # [n_q, hd]
        acc[:] = acc[:] * alpha[:, None] + pv
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)

    @pl.when(bj == nb - 1)
    def _finish():
        l = l_scr[:, 0]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / safe_l[:, None]).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,            # [b, 1, n_q, hd]
    k_pool: jnp.ndarray,       # [num_blocks, block_size, n_kv, hd]
    v_pool: jnp.ndarray,       # [num_blocks, block_size, n_kv, hd]
    block_table: jnp.ndarray,  # [b, blocks_per_slot] int32 physical ids
    q_positions: jnp.ndarray,  # [b] int32 — each row's cursor
    kv_mask: jnp.ndarray | None = None,  # [b, blocks_per_slot*block_size]
    *,
    window: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One-token-per-row attention through each row's block table.

    HBM reads per row are `ceil((cursor+1)/block_size)` pool blocks
    (bounded below by the sliding window's first block), not the full
    `blocks_per_slot` window the XLA gather touches.
    """
    if interpret is None:
        interpret = _interpret_default()
    b, sq, n_q, hd = q.shape
    if sq != 1:
        raise ValueError(
            f"paged_decode_attention is s=1 only, got sq={sq}")
    if k_pool.shape != v_pool.shape:
        raise ValueError(
            f"k_pool/v_pool shapes disagree: {k_pool.shape} vs "
            f"{v_pool.shape}")
    num_blocks, block_size, n_kv, hd_kv = k_pool.shape
    if hd_kv != hd:
        raise ValueError(
            f"head dim mismatch: q has {hd}, pool has {hd_kv}")
    if n_q % n_kv:
        raise ValueError(f"{n_q} query heads not grouped by {n_kv} kv")
    group = n_q // n_kv
    if block_table.ndim != 2 or block_table.shape[0] != b:
        raise ValueError(
            f"block_table must be [b={b}, blocks_per_slot], got "
            f"{block_table.shape}")
    nb = block_table.shape[1]
    width = nb * block_size
    if q_positions.shape != (b,):
        raise ValueError(
            f"q_positions must be [b={b}], got {q_positions.shape}")
    if kv_mask is None:
        kv_mask = jnp.ones((b, width), bool)
    if kv_mask.shape != (b, width):
        raise ValueError(
            f"kv_mask must be [b={b}, blocks_per_slot*block_size="
            f"{width}], got {kv_mask.shape}")
    positions = q_positions.astype(jnp.int32)
    table = block_table.astype(jnp.int32)

    # Clamped LOGICAL block index: iterations outside a row's live
    # range re-reference a boundary block, whose PHYSICAL id then
    # repeats — consecutive equal indices skip the DMA, which is where
    # the fill-proportional saving comes from. The live range is
    # [first block the window can see, cursor block]; the table's
    # trash-block tail is never read.
    def _clamp(bj, pos):
        hi = pos // block_size
        if window is None:
            return jnp.minimum(bj, hi)
        lo = jnp.maximum((pos - window + 1) // block_size, 0)
        return jnp.clip(bj, lo, hi)

    def kv_map(b_i, bj, pos_ref, tab_ref):
        # The indirection: logical block -> physical pool block.
        return (tab_ref[b_i, _clamp(bj, pos_ref[b_i])], 0, 0, 0)

    def mask_map(b_i, bj, pos_ref, tab_ref):
        # The mask is laid out logically, so no table lookup here.
        return (b_i, _clamp(bj, pos_ref[b_i]))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, 1, n_q, hd),
                         lambda b_i, bj, pos_ref, tab_ref: (b_i, 0, 0, 0)),
            pl.BlockSpec((1, block_size, n_kv, hd), kv_map),
            pl.BlockSpec((1, block_size, n_kv, hd), kv_map),
            pl.BlockSpec((1, block_size), mask_map),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, n_q, hd),
            lambda b_i, bj, pos_ref, tab_ref: (b_i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_q, hd), jnp.float32),
            pltpu.VMEM((n_q, 128), jnp.float32),
            pltpu.VMEM((n_q, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel, scale=hd**-0.5, window=window, block_size=block_size,
        nb=nb, n_kv=n_kv, group=group,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(positions, table, q, k_pool, v_pool, kv_mask)
