"""Rotary position embeddings (RoPE), Llama-3 style with NTK scaling hooks."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int,
    *,
    theta: float = 500000.0,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """Inverse frequencies for RoPE. Llama-3 uses theta=500000."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return (1.0 / (theta**exponents)).astype(dtype)


def apply_rope(
    x: jnp.ndarray,          # [batch, seq, heads, head_dim]
    positions: jnp.ndarray,  # [batch, seq] int32
    inv_freq: jnp.ndarray,   # [head_dim // 2]
) -> jnp.ndarray:
    """Rotate (pairs-split convention: first half/second half, as Llama).

    fp32 sin/cos for precision; result cast back to x.dtype.
    """
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [b, s, hd/2]
    sin = jnp.sin(angles)[:, :, None, :]
    cos = jnp.cos(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)
