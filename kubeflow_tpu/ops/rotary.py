"""Rotary position embeddings (RoPE), Llama-3 style with NTK scaling hooks."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int,
    *,
    theta: float = 500000.0,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """Inverse frequencies for RoPE. Llama-3 uses theta=500000."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return (1.0 / (theta**exponents)).astype(dtype)


def apply_rope(
    x: jnp.ndarray,          # [batch, seq, heads, head_dim]
    positions: jnp.ndarray,  # [batch, seq] int32
    inv_freq: jnp.ndarray,   # [head_dim // 2]
) -> jnp.ndarray:
    """Rotate (pairs-split convention: first half/second half, as Llama).

    fp32 sin/cos for precision; result cast back to x.dtype.

    Implemented as elementwise multiplies plus a fixed signed
    PERMUTATION gather along head_dim — deliberately no split/
    concatenate. Under tensor parallelism the fused QKV projections
    leave head_dim sharded whenever the head count doesn't divide the
    tensor axis (e.g. 2 KV heads on tensor=4), and a concatenate whose
    operands are sharded along the concat axis forces the SPMD
    partitioner into "involuntary full rematerialization" — slow on
    TPU, and numerically WRONG on the multi-device CPU backend (the
    tensor-parallel parity bug: sharded generate emitted different
    tokens from the first prefill token). Gathers with constant
    indices partition cleanly; unsharded numerics are bit-identical
    to the split/concat form.
    """
    hd = x.shape[-1]
    hd2 = hd // 2
    idx = np.arange(hd)
    angles = (positions[..., None].astype(jnp.float32)
              * inv_freq[idx % hd2])             # [b, s, hd]
    sin = jnp.sin(angles)[:, :, None, :]
    cos = jnp.cos(angles)[:, :, None, :]
    xf = x.astype(jnp.float32)
    # rotate_half(x) = [-x2, x1]: partner index + sign, one gather.
    rotated_half = xf[..., (idx + hd2) % hd] * np.where(
        idx < hd2, -1.0, 1.0).astype(np.float32)
    return (xf * cos + rotated_half * sin).astype(x.dtype)
