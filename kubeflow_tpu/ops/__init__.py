"""TPU-friendly ops: norms, rotary embeddings, attention dispatch.

Hot ops get Pallas TPU kernels (flash attention); everything else is plain
jnp left to XLA fusion — hand-scheduling what the compiler already fuses
would only hurt (see /opt/skills/guides/pallas_guide.md).
"""

from kubeflow_tpu.ops.norms import rms_norm
from kubeflow_tpu.ops.rotary import apply_rope, rope_frequencies
from kubeflow_tpu.ops.attention import (
    dot_product_attention,
    paged_attention,
    paged_prefill_attention,
    resolve_paged_attention_impl,
    resolve_paged_prefill_impl,
)
