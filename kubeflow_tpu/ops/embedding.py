"""Embedding lookup, mesh-aware — shared by training and serving.

With the table sharded (vocab→tensor, embed→fsdp), a gather's output
sharding clashes with the batch-sharded activation constraint and XLA's
SPMD partitioner falls back to full rematerialization
(replicate-then-reshard — the "Involuntary full rematerialization"
warning). At Gemma vocab scale (256k) that replication is ~2 GB of
bf16 table per chip per step. Under a sharding mesh the lookup is
therefore a one-hot contraction riding the MXU: vocab contracts (psum
over tensor) and sharding composes cleanly. On a trivial mesh (single
chip / pure DP, table effectively replicated) the gather is strictly
cheaper — the one-hot adds a full vocab matmul (~2% step time at 32k
vocab) for nothing — so it stays a gather there.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubeflow_tpu.parallel import mesh as mesh_lib


def embed_lookup(table: jnp.ndarray, tokens: jnp.ndarray,
                 dtype) -> jnp.ndarray:
    """tokens [..., s] int32 -> activations [..., s, embed] in `dtype`."""
    mesh = mesh_lib.get_abstract_mesh()
    sharded = mesh is not None and any(
        mesh.shape.get(ax, 1) > 1
        for ax in (mesh_lib.FSDP_AXIS, mesh_lib.TENSOR_AXIS)
    )
    if not sharded:
        return table.astype(dtype)[tokens]
    onehot = jax.nn.one_hot(tokens, table.shape[0], dtype=dtype)
    return onehot @ table.astype(dtype)
