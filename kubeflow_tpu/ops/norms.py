"""Normalization ops."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-6) -> jnp.ndarray:
    """LayerNorm (mean-centered) in fp32 accumulation, cast back.

    ViT-style: weight multiplies, bias adds; ones/zeros init is identity.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(dtype)


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm in fp32 accumulation, cast back to input dtype.

    XLA fuses this into neighboring ops; no kernel needed. Computed in
    float32 regardless of activation dtype (bf16-safe). Uses the Llama
    convention of a (1 + w) scale so zero-init weights are identity.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * (1.0 + weight.astype(jnp.float32))).astype(dtype)
