"""Attention: XLA reference path + TPU Pallas flash-attention dispatch.

Design: one public `dot_product_attention` that dispatches by backend.
- CPU / debugging: pure-XLA grouped-query attention with fp32 logits.
- TPU: Pallas flash attention kernel (kubeflow_tpu.ops.pallas.flash_attention)
  for long sequences; falls back to XLA for short ones (XLA's fused
  attention is already good below ~1k tokens).

The XLA path never materializes repeated KV heads: queries are reshaped to
[batch, q_per_kv, kv_heads, ...] and contracted against the kv heads
directly — keeps HBM traffic at the GQA level, which is the point of GQA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -2.0**30  # large-but-finite: avoids NaNs from (-inf) - (-inf)

# Trace-time dispatch counters. `dot_product_attention` runs in Python at
# trace time, so these count how many traced call sites took each impl —
# which is how bench.py *proves* the long-seq preset routed through the
# Pallas flash kernel instead of silently falling back to XLA.
_impl_counts = {"flash": 0, "xla": 0, "decode": 0, "paged": 0}


def reset_impl_counts() -> None:
    for key in _impl_counts:
        _impl_counts[key] = 0


def impl_counts() -> dict[str, int]:
    return dict(_impl_counts)


def _xla_attention(
    q: jnp.ndarray,            # [b, sq, n_q, hd]
    k: jnp.ndarray,            # [b, skv, n_kv, hd]
    v: jnp.ndarray,            # [b, skv, n_kv, hd]
    q_positions: jnp.ndarray,  # [b, sq]
    kv_positions: jnp.ndarray, # [b, skv]
    *,
    causal: bool,
    kv_mask: jnp.ndarray | None,  # [b, skv] bool, False = padded/invalid
    window: int | None = None,
) -> jnp.ndarray:
    b, sq, n_q, hd = q.shape
    n_kv = k.shape[2]
    assert n_q % n_kv == 0, (n_q, n_kv)
    group = n_q // n_kv
    scale = hd**-0.5

    qg = q.reshape(b, sq, n_kv, group, hd)
    # logits: [b, n_kv, group, sq, skv] in fp32
    logits = jnp.einsum(
        "bsngh,btnh->bngst", qg.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale

    mask = jnp.ones((b, sq, k.shape[1]), dtype=bool)
    if causal:
        mask &= q_positions[:, :, None] >= kv_positions[:, None, :]
    if window is not None:
        # sliding window: each query attends its last `window` positions
        mask &= (q_positions[:, :, None]
                 - kv_positions[:, None, :]) < window
    if kv_mask is not None:
        mask &= kv_mask[:, None, :]
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bngst,btnh->bsngh", probs, v.astype(jnp.float32))
    return out.reshape(b, sq, n_q, hd).astype(q.dtype)


def _flash_kernel_available() -> bool:
    try:
        from kubeflow_tpu.ops.pallas import flash_attention  # noqa: F401
        return True
    except ImportError:
        return False


def _decode_kernel_available() -> bool:
    try:
        from kubeflow_tpu.ops.pallas import decode_attention  # noqa: F401
        return True
    except ImportError:
        return False


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    *,
    causal: bool = True,
    kv_mask: jnp.ndarray | None = None,
    window: int | None = None,
    impl: str = "auto",
    contiguous_positions: bool = False,
) -> jnp.ndarray:
    """Grouped-query attention. `window` limits each query to its last
    `window` positions (sliding-window attention; requires causal) —
    supported by both impls, position-based in XLA, index-based in flash.

    impl: "auto" | "xla" | "flash" | "decode". "auto" picks, on TPU:
    the Pallas flash kernel for long sequences when safe (kernel
    present, no kv_mask, positions declared contiguous), or the fused
    decode kernel for single-token causal steps against a >=256-cell
    cache (again only with `contiguous_positions=True` — it masks by
    cache cell index against each row's cursor). Packed sequences with
    per-segment position resets, and caches whose cell index is not
    the token position, MUST take the XLA path, which masks by the
    actual position tensors.
    """
    if window is not None and not causal:
        raise ValueError("sliding window requires causal attention")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        long_seq = q.shape[1] >= 1024 and q.shape[1] % 512 == 0
        same_len = q.shape[1] == k.shape[1]
        # One query token against a longer cache = the serving decode
        # step. The fused kernel skips cache blocks past each row's
        # cursor (HBM traffic tracks fill, not max_len) — worthwhile
        # once the cache is big enough to block (>= 256 cells). It
        # masks by CACHE CELL INDEX, so like flash it needs the
        # caller's declaration that positions are cell indices
        # (`contiguous_positions=True`) — a packed/rotated cache whose
        # cell index != token position MUST take the XLA path, which
        # compares the actual position tensors.
        decode_step = (q.shape[1] == 1 and k.shape[1] >= 256
                       and causal and contiguous_positions)
        if (on_tpu and long_seq and same_len and causal
                and kv_mask is None and contiguous_positions
                and _flash_kernel_available()):
            impl = "flash"
        elif on_tpu and decode_step and _decode_kernel_available():
            impl = "decode"
        else:
            impl = "xla"
    _impl_counts[impl] = _impl_counts.get(impl, 0) + 1
    if impl == "decode":
        if q.shape[1] != 1:
            raise ValueError("impl='decode' is for single-token steps")
        if not contiguous_positions:
            raise ValueError(
                "impl='decode' masks by cache cell index: the caller "
                "must declare cell index == token position "
                "(contiguous_positions=True); packed/rotated caches "
                "must use impl='xla'")
        if not causal:
            # the kernel masks idx <= cursor unconditionally; a
            # bidirectional single-query lookup would silently lose
            # the cells past the cursor (same discipline as the
            # flash door's unsupported-combo raises)
            raise ValueError("impl='decode' is causal-only")
        from kubeflow_tpu.ops.pallas.decode_attention import (
            decode_attention,
        )

        return decode_attention(
            q, k, v, q_positions[:, 0], kv_mask, window=window)
    if impl == "flash":
        if kv_mask is not None or not contiguous_positions:
            raise ValueError(
                "impl='flash' masks by row/col index only: it supports "
                "neither kv_mask nor non-contiguous positions (pass "
                "contiguous_positions=True for plain causal batches, or "
                "use impl='xla')"
            )
        from kubeflow_tpu.ops.pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, window=window)
    return _xla_attention(
        q, k, v, q_positions, kv_positions, causal=causal,
        kv_mask=kv_mask, window=window,
    )


def paged_attention(
    q: jnp.ndarray,            # [b, 1, n_q, hd] — single decode step
    k_pool: jnp.ndarray,       # [num_blocks, block_size, n_kv, hd]
    v_pool: jnp.ndarray,       # [num_blocks, block_size, n_kv, hd]
    block_table: jnp.ndarray,  # [b, blocks_per_slot] int32 physical ids
    q_positions: jnp.ndarray,  # [b, 1]
    kv_positions: jnp.ndarray, # [b, blocks_per_slot * block_size]
    *,
    causal: bool = True,
    kv_mask: jnp.ndarray | None = None,  # [b, blocks_per_slot * block_size]
    window: int | None = None,
) -> jnp.ndarray:
    """Decode attention against a paged KV cache.

    Each row's K/V is gathered from a shared block pool through its
    block table, then fed to the same grouped-query attention as the
    dense path. Because masked cells contribute an exact +0.0 to the
    softmax sums (NEG_INF logits underflow to 0.0 in fp32 exp), the
    gathered layout is bit-identical to a dense cache holding the same
    tokens at the same logical cells — which is what lets the tests
    compare paged decode against dense decode exactly.

    The gather materializes `[b, blocks_per_slot * block_size]` of K/V
    per layer — fine for XLA/CPU and short-to-mid contexts; a fused
    Pallas kernel that walks the table in-kernel is the TPU follow-up
    (see docs/perf-notes.md).
    """
    b = q.shape[0]
    blocks_per_slot = block_table.shape[1]
    block_size, n_kv, hd = k_pool.shape[1:]
    width = blocks_per_slot * block_size
    k = k_pool[block_table].reshape(b, width, n_kv, hd)
    v = v_pool[block_table].reshape(b, width, n_kv, hd)
    _impl_counts["paged"] += 1
    # Cell index == logical token position by construction (insert-time
    # compaction strips prefill padding), so positions are contiguous.
    return dot_product_attention(
        q, k, v, q_positions, kv_positions, causal=causal,
        kv_mask=kv_mask, window=window, contiguous_positions=True,
    )
