"""Attention: XLA reference path + TPU Pallas flash-attention dispatch.

Design: one public `dot_product_attention` that dispatches by backend.
- CPU / debugging: pure-XLA grouped-query attention with fp32 logits.
- TPU: Pallas flash attention kernel (kubeflow_tpu.ops.pallas.flash_attention)
  for long sequences; falls back to XLA for short ones (XLA's fused
  attention is already good below ~1k tokens).

The XLA path never materializes repeated KV heads: queries are reshaped to
[batch, q_per_kv, kv_heads, ...] and contracted against the kv heads
directly — keeps HBM traffic at the GQA level, which is the point of GQA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -2.0**30  # large-but-finite: avoids NaNs from (-inf) - (-inf)

# Trace-time dispatch counters. `dot_product_attention` runs in Python at
# trace time, so these count how many traced call sites took each impl —
# which is how bench.py *proves* the long-seq preset routed through the
# Pallas flash kernel instead of silently falling back to XLA.
_impl_counts = {"flash": 0, "xla": 0, "decode": 0, "paged": 0,
                "paged_xla": 0, "paged_pallas": 0, "paged_prefill": 0,
                "paged_prefill_xla": 0, "paged_prefill_pallas": 0}


def reset_impl_counts() -> None:
    for key in _impl_counts:
        _impl_counts[key] = 0


def impl_counts() -> dict[str, int]:
    return dict(_impl_counts)


def _xla_attention(
    q: jnp.ndarray,            # [b, sq, n_q, hd]
    k: jnp.ndarray,            # [b, skv, n_kv, hd]
    v: jnp.ndarray,            # [b, skv, n_kv, hd]
    q_positions: jnp.ndarray,  # [b, sq]
    kv_positions: jnp.ndarray, # [b, skv]
    *,
    causal: bool,
    kv_mask: jnp.ndarray | None,  # [b, skv] bool, False = padded/invalid
    window: int | None = None,
) -> jnp.ndarray:
    b, sq, n_q, hd = q.shape
    n_kv = k.shape[2]
    assert n_q % n_kv == 0, (n_q, n_kv)
    group = n_q // n_kv
    scale = hd**-0.5

    qg = q.reshape(b, sq, n_kv, group, hd)
    # logits: [b, n_kv, group, sq, skv] in fp32
    logits = jnp.einsum(
        "bsngh,btnh->bngst", qg.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale

    mask = jnp.ones((b, sq, k.shape[1]), dtype=bool)
    if causal:
        mask &= q_positions[:, :, None] >= kv_positions[:, None, :]
    if window is not None:
        # sliding window: each query attends its last `window` positions
        mask &= (q_positions[:, :, None]
                 - kv_positions[:, None, :]) < window
    if kv_mask is not None:
        mask &= kv_mask[:, None, :]
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bngst,btnh->bsngh", probs, v.astype(jnp.float32))
    return out.reshape(b, sq, n_q, hd).astype(q.dtype)


def _flash_kernel_available() -> bool:
    try:
        from kubeflow_tpu.ops.pallas import flash_attention  # noqa: F401
        return True
    except ImportError:
        return False


def _decode_kernel_available() -> bool:
    try:
        from kubeflow_tpu.ops.pallas import decode_attention  # noqa: F401
        return True
    except ImportError:
        return False


def _paged_kernel_available() -> bool:
    try:
        from kubeflow_tpu.ops.pallas import paged_attention  # noqa: F401
        return True
    except ImportError:
        return False


def _prefill_append_kernel_available() -> bool:
    try:
        from kubeflow_tpu.ops.pallas import prefill_append  # noqa: F401
        return True
    except ImportError:
        return False


def resolve_paged_prefill_impl(impl: str) -> str:
    """Resolve a `paged_prefill_attention` impl request to "xla" or
    "pallas" — same policy as `resolve_paged_attention_impl`: "auto" is
    the fused Pallas kernel on TPU when it imports, the XLA
    scatter+gather everywhere else (CPU runs the kernel only in
    interpret mode, the numerics/test vehicle)."""
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"paged prefill impl must be 'auto', 'xla' or 'pallas', "
            f"got {impl!r}")
    if impl == "auto":
        if (jax.default_backend() == "tpu"
                and _prefill_append_kernel_available()):
            return "pallas"
        return "xla"
    return impl


def resolve_paged_attention_impl(impl: str) -> str:
    """Resolve a `paged_attention` impl request to "xla" or "pallas".

    "auto" picks the fused Pallas kernel on TPU when present (falling
    back to the gather if the kernel fails to import), the XLA gather
    everywhere else — CPU runs the kernel only in interpret mode, which
    is a numerics/test vehicle, not a fast path. Resolving once at
    engine construction (rather than per trace) is what lets serving
    label its metrics with the impl that actually runs.
    """
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"paged attention impl must be 'auto', 'xla' or 'pallas', "
            f"got {impl!r}")
    if impl == "auto":
        if jax.default_backend() == "tpu" and _paged_kernel_available():
            return "pallas"
        return "xla"
    return impl


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    *,
    causal: bool = True,
    kv_mask: jnp.ndarray | None = None,
    window: int | None = None,
    impl: str = "auto",
    contiguous_positions: bool = False,
) -> jnp.ndarray:
    """Grouped-query attention. `window` limits each query to its last
    `window` positions (sliding-window attention; requires causal) —
    supported by both impls, position-based in XLA, index-based in flash.

    impl: "auto" | "xla" | "flash" | "decode". "auto" picks, on TPU:
    the Pallas flash kernel for long sequences when safe (kernel
    present, no kv_mask, positions declared contiguous), or the fused
    decode kernel for single-token causal steps against a >=256-cell
    cache (again only with `contiguous_positions=True` — it masks by
    cache cell index against each row's cursor). Packed sequences with
    per-segment position resets, and caches whose cell index is not
    the token position, MUST take the XLA path, which masks by the
    actual position tensors.
    """
    if window is not None and not causal:
        raise ValueError("sliding window requires causal attention")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        long_seq = q.shape[1] >= 1024 and q.shape[1] % 512 == 0
        same_len = q.shape[1] == k.shape[1]
        # One query token against a longer cache = the serving decode
        # step. The fused kernel skips cache blocks past each row's
        # cursor (HBM traffic tracks fill, not max_len) — worthwhile
        # once the cache is big enough to block (>= 256 cells). It
        # masks by CACHE CELL INDEX, so like flash it needs the
        # caller's declaration that positions are cell indices
        # (`contiguous_positions=True`) — a packed/rotated cache whose
        # cell index != token position MUST take the XLA path, which
        # compares the actual position tensors.
        decode_step = (q.shape[1] == 1 and k.shape[1] >= 256
                       and causal and contiguous_positions)
        if (on_tpu and long_seq and same_len and causal
                and kv_mask is None and contiguous_positions
                and _flash_kernel_available()):
            impl = "flash"
        elif on_tpu and decode_step and _decode_kernel_available():
            impl = "decode"
        else:
            impl = "xla"
    _impl_counts[impl] = _impl_counts.get(impl, 0) + 1
    if impl == "decode":
        if q.shape[1] != 1:
            raise ValueError("impl='decode' is for single-token steps")
        if not contiguous_positions:
            raise ValueError(
                "impl='decode' masks by cache cell index: the caller "
                "must declare cell index == token position "
                "(contiguous_positions=True); packed/rotated caches "
                "must use impl='xla'")
        if not causal:
            # the kernel masks idx <= cursor unconditionally; a
            # bidirectional single-query lookup would silently lose
            # the cells past the cursor (same discipline as the
            # flash door's unsupported-combo raises)
            raise ValueError("impl='decode' is causal-only")
        from kubeflow_tpu.ops.pallas.decode_attention import (
            decode_attention,
        )

        return decode_attention(
            q, k, v, q_positions[:, 0], kv_mask, window=window)
    if impl == "flash":
        if kv_mask is not None or not contiguous_positions:
            raise ValueError(
                "impl='flash' masks by row/col index only: it supports "
                "neither kv_mask nor non-contiguous positions (pass "
                "contiguous_positions=True for plain causal batches, or "
                "use impl='xla')"
            )
        from kubeflow_tpu.ops.pallas.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, window=window)
    return _xla_attention(
        q, k, v, q_positions, kv_positions, causal=causal,
        kv_mask=kv_mask, window=window,
    )


def paged_attention(
    q: jnp.ndarray,            # [b, 1, n_q, hd] — single decode step
    k_pool: jnp.ndarray,       # [num_blocks, block_size, n_kv, hd]
    v_pool: jnp.ndarray,       # [num_blocks, block_size, n_kv, hd]
    block_table: jnp.ndarray,  # [b, blocks_per_slot] int32 physical ids
    q_positions: jnp.ndarray,  # [b, 1]
    kv_positions: jnp.ndarray, # [b, blocks_per_slot * block_size]
    *,
    causal: bool = True,
    kv_mask: jnp.ndarray | None = None,  # [b, blocks_per_slot * block_size]
    window: int | None = None,
    impl: str = "xla",
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention against a paged KV cache.

    impl: "auto" | "xla" | "pallas".

    - "xla" (default): each row's K/V is gathered from the block pool
      through its table, then fed to the same grouped-query attention
      as the dense path. Because masked cells contribute an exact +0.0
      to the softmax sums (NEG_INF logits underflow to 0.0 in fp32
      exp), the gathered layout is bit-identical to a dense cache
      holding the same tokens at the same logical cells — which is
      what lets the tests compare paged decode against dense decode
      exactly. The gather materializes the full
      `[b, blocks_per_slot * block_size]` K/V window per layer — fine
      for CPU and short-to-mid contexts, HBM-wasteful at long max_len.
    - "pallas": the fused kernel (ops/pallas/paged_attention.py) walks
      the block table IN-KERNEL — scalar-prefetched cursors clamp the
      DMA range to each row's live blocks, so HBM traffic tracks cache
      fill instead of the full window. Causal-only (it masks by cell
      index against the cursor, so it also requires the pool's
      cell-index == token-position invariant, which insert-time
      compaction guarantees). `interpret` forces Pallas interpret mode
      (default: on for non-TPU backends) — the CPU test vehicle.
    - "auto": pallas on TPU when the kernel imports, xla otherwise.

    The two impls agree to fp32 tolerance (online-softmax merge vs
    single-pass softmax); tests/test_paged_attention_kernel.py pins
    the kernel against this gather path as the numerics oracle.
    """
    b = q.shape[0]
    if block_table.ndim != 2 or block_table.shape[0] != b:
        raise ValueError(
            f"block_table must be [b={b}, blocks_per_slot], got "
            f"{block_table.shape}")
    if k_pool.shape != v_pool.shape:
        raise ValueError(
            f"k_pool/v_pool shapes disagree: {k_pool.shape} vs "
            f"{v_pool.shape}")
    blocks_per_slot = block_table.shape[1]
    block_size, n_kv, hd = k_pool.shape[1:]
    width = blocks_per_slot * block_size
    # Geometry mismatches (a pool rebuilt with a different block_size
    # than the tables/masks were laid out for) used to surface as an
    # opaque reshape/gather shape error deep inside jit; check here
    # with the actual numbers instead.
    if kv_positions.shape != (b, width):
        raise ValueError(
            f"kv_positions shape {kv_positions.shape} does not match "
            f"blocks_per_slot * block_size = {blocks_per_slot} * "
            f"{block_size} = {width} (pool {k_pool.shape}, table "
            f"{block_table.shape})")
    if kv_mask is not None and kv_mask.shape != (b, width):
        raise ValueError(
            f"kv_mask shape {kv_mask.shape} does not match "
            f"blocks_per_slot * block_size = {blocks_per_slot} * "
            f"{block_size} = {width}")
    impl = resolve_paged_attention_impl(impl)
    _impl_counts["paged"] += 1
    _impl_counts["paged_" + impl] += 1
    if impl == "pallas":
        if not causal:
            # the kernel masks idx <= cursor unconditionally (same
            # door discipline as impl='decode')
            raise ValueError("impl='pallas' paged attention is "
                             "causal-only; use impl='xla'")
        from kubeflow_tpu.ops.pallas.paged_attention import (
            paged_decode_attention,
        )

        return paged_decode_attention(
            q, k_pool, v_pool, block_table, q_positions[:, 0],
            kv_mask, window=window, interpret=interpret)
    k = k_pool[block_table].reshape(b, width, n_kv, hd)
    v = v_pool[block_table].reshape(b, width, n_kv, hd)
    # Cell index == logical token position by construction (insert-time
    # compaction strips prefill padding), so positions are contiguous.
    return dot_product_attention(
        q, k, v, q_positions, kv_positions, causal=causal,
        kv_mask=kv_mask, window=window, contiguous_positions=True,
    )


def paged_prefill_attention(
    q: jnp.ndarray,            # [b, s, n_q, hd] — s new tokens per row
    k_new: jnp.ndarray,        # [b, s, n_kv, hd]
    v_new: jnp.ndarray,        # [b, s, n_kv, hd]
    k_pool: jnp.ndarray,       # [num_blocks, block_size, n_kv, hd]
    v_pool: jnp.ndarray,       # [num_blocks, block_size, n_kv, hd]
    block_table: jnp.ndarray,  # [b, blocks_per_slot] int32 physical ids
    q_start: jnp.ndarray,      # [b] int32 — append cursor per row
    q_lens: jnp.ndarray | None = None,  # [b] int32 — valid new tokens
    *,
    kv_mask: jnp.ndarray | None = None,  # [b, blocks_per_slot*block_size]
    window: int | None = None,
    impl: str = "xla",
    interpret: bool | None = None,
):
    """Append s new tokens per row into the paged KV pool and attend
    them against everything written so far. Returns
    `(out [b, s, n_q, hd], k_pool, v_pool)` — the serving primitive
    behind chunked prefill (the chunk's tokens) and speculative verify
    (the γ+1 draft-window tokens).

    Row r's token t lands at logical cell `q_start[r] + t` (physical:
    through the row's block table) and attends causally by absolute
    cell index — cell index == logical token position is a
    precondition, as for `paged_attention`. Tokens with `t >= q_lens[r]`
    are group padding: their K/V is routed to the trash block and their
    attention output is garbage the caller discards.

    impl: "auto" | "xla" | "pallas".
    - "xla" (default): scatter the new cells through the table with
      `.at[].set`, then gather the full window and run the shared XLA
      grouped-query attention — correct everywhere, but the new cells
      round-trip through HBM and the dead tail streams every chunk.
    - "pallas": the fused kernel (ops/pallas/prefill_append.py) merges
      the new tokens into each live block in-register, writes the pool
      in place (input_output_aliases) and attends in the same pass —
      one read+write of `ceil((q_start+s)/block_size)` blocks per row.
      Causal-only. `interpret` forces interpret mode (default: on for
      non-TPU backends) — the CPU test vehicle.
    - "auto": pallas on TPU when the kernel imports, xla otherwise.
    """
    b, s, n_q, hd = q.shape
    n_kv = k_pool.shape[2]
    if k_pool.shape != v_pool.shape:
        raise ValueError(
            f"k_pool/v_pool shapes disagree: {k_pool.shape} vs "
            f"{v_pool.shape}")
    if block_table.ndim != 2 or block_table.shape[0] != b:
        raise ValueError(
            f"block_table must be [b={b}, blocks_per_slot], got "
            f"{block_table.shape}")
    blocks_per_slot = block_table.shape[1]
    block_size = k_pool.shape[1]
    width = blocks_per_slot * block_size
    if q_lens is None:
        q_lens = jnp.full((b,), s, jnp.int32)
    if kv_mask is not None and kv_mask.shape != (b, width):
        raise ValueError(
            f"kv_mask shape {kv_mask.shape} does not match "
            f"blocks_per_slot * block_size = {blocks_per_slot} * "
            f"{block_size} = {width}")
    impl = resolve_paged_prefill_impl(impl)
    _impl_counts["paged_prefill"] += 1
    _impl_counts["paged_prefill_" + impl] += 1
    if impl == "pallas":
        from kubeflow_tpu.ops.pallas.prefill_append import (
            paged_prefill_append,
        )

        return paged_prefill_append(
            q, k_new, v_new, k_pool, v_pool, block_table,
            q_start, q_lens, kv_mask, window=window,
            interpret=interpret)
    # XLA reference: scatter the new cells through the table (invalid
    # tokens to the trash block — the pool's garbage-write convention),
    # then gather and attend with the shared fp32 path.
    pos = (q_start[:, None].astype(jnp.int32)
           + jnp.arange(s, dtype=jnp.int32)[None, :])
    valid = jnp.arange(s)[None, :] < q_lens[:, None]
    safe = jnp.minimum(pos, width - 1)
    blk = jnp.take_along_axis(block_table, safe // block_size, axis=1)
    blk = jnp.where(valid, blk, 0)
    off = safe % block_size
    k_pool = k_pool.at[blk, off].set(k_new.astype(k_pool.dtype))
    v_pool = v_pool.at[blk, off].set(v_new.astype(v_pool.dtype))
    k = k_pool[block_table].reshape(b, width, n_kv, hd)
    v = v_pool[block_table].reshape(b, width, n_kv, hd)
    kv_positions = jnp.broadcast_to(
        jnp.arange(width, dtype=jnp.int32)[None, :], (b, width))
    out = _xla_attention(
        q, k, v, pos, kv_positions, causal=True, kv_mask=kv_mask,
        window=window)
    return out, k_pool, v_pool
