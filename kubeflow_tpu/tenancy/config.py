"""Tenant specs and where they come from.

A `TenantSpec` is the QoS contract for one tenant: its fair-share
weight, priority class, rate limits, and KV-pool share. A
`TenancyConfig` is the full tenant table plus the safe `default`
tenant every unlabeled (or unknown) request resolves to — resolving
to `default` instead of minting a spec per unknown name is what keeps
queue/metric cardinality bounded by CONFIG, not by traffic.

Specs load from a JSON file (`load_config`) or bridge from control-
plane Profile objects: a Profile annotated with
`kubeflow-tpu.dev/serving-tenant` becomes a tenant named after the
profile, with the annotation value (a JSON object of spec fields)
overriding the defaults.
"""

from __future__ import annotations

import dataclasses
import json

# Strict priority classes, highest first: the scheduler serves a lower
# class only when every higher class is empty (or rate-paced).
PRIORITIES = ("interactive", "standard", "batch")

DEFAULT_TENANT = "default"

# Profile -> tenant bridge: annotation value is "" (all defaults) or a
# JSON object of TenantSpec fields; the tenant name is the profile name.
SERVING_TENANT_ANNOTATION = "kubeflow-tpu.dev/serving-tenant"


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """QoS contract for one tenant. Rates <= 0 mean unlimited; a burst
    of 0 defaults to max(1, rate). `kv_block_share` bounds the fraction
    of the KV pool this tenant's CONCURRENT requests may hold (1.0 =
    uncapped); `prefix_isolation` salts the radix prefix cache with the
    tenant id so cross-tenant prompts can never share (or time) cache
    entries."""

    name: str
    weight: float = 1.0
    priority: str = "standard"
    requests_per_s: float = 0.0
    request_burst: float = 0.0
    tokens_per_s: float = 0.0
    token_burst: float = 0.0
    kv_block_share: float = 1.0
    prefix_isolation: bool = False

    def __post_init__(self):
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        if self.priority not in PRIORITIES:
            raise ValueError(
                f"tenant {self.name!r}: priority {self.priority!r} "
                f"not in {PRIORITIES}")
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, "
                f"got {self.weight}")
        if not 0 < self.kv_block_share <= 1:
            raise ValueError(
                f"tenant {self.name!r}: kv_block_share must be in "
                f"(0, 1], got {self.kv_block_share}")


_SPEC_FIELDS = {f.name for f in dataclasses.fields(TenantSpec)} - {"name"}


def spec_from_dict(name: str, data: dict) -> TenantSpec:
    unknown = set(data) - _SPEC_FIELDS
    if unknown:
        raise ValueError(
            f"tenant {name!r}: unknown spec field(s) {sorted(unknown)}; "
            f"valid: {sorted(_SPEC_FIELDS)}")
    return TenantSpec(name=name, **data)


class TenancyConfig:
    """The tenant table. Always contains a `default` tenant; `resolve`
    maps any request identity (including "" and names nobody
    configured) onto a configured spec."""

    def __init__(self, tenants=(), default: TenantSpec | None = None):
        self.tenants: dict[str, TenantSpec] = {}
        for spec in tenants:
            if spec.name in self.tenants:
                raise ValueError(f"duplicate tenant {spec.name!r}")
            self.tenants[spec.name] = spec
        if default is not None:
            if default.name != DEFAULT_TENANT:
                raise ValueError(
                    f"default tenant must be named {DEFAULT_TENANT!r}, "
                    f"got {default.name!r}")
            self.tenants[DEFAULT_TENANT] = default
        self.tenants.setdefault(
            DEFAULT_TENANT, TenantSpec(name=DEFAULT_TENANT))

    @property
    def default(self) -> TenantSpec:
        return self.tenants[DEFAULT_TENANT]

    def resolve(self, name: str) -> TenantSpec:
        """Spec for a request identity. Unlabeled and UNKNOWN names both
        land on `default` — an unrecognized `X-Tenant` must not mint
        per-value queues or metric series (unbounded cardinality is a
        DoS vector all by itself)."""
        return self.tenants.get(name or DEFAULT_TENANT, self.default)

    def names(self) -> list[str]:
        return sorted(self.tenants)


def config_from_dict(data: dict) -> TenancyConfig:
    """`{"tenants": {name: {spec fields}}, "default": {spec fields}}` —
    the on-disk shape `--tenants file.json` loads."""
    tenants = [spec_from_dict(name, dict(fields or {}))
               for name, fields in (data.get("tenants") or {}).items()
               if name != DEFAULT_TENANT]
    default = None
    merged = dict(data.get("tenants") or {}).get(DEFAULT_TENANT)
    if data.get("default") is not None:
        merged = data["default"]
    if merged is not None:
        default = spec_from_dict(DEFAULT_TENANT, dict(merged))
    return TenancyConfig(tenants, default=default)


def load_config(path) -> TenancyConfig:
    with open(path, encoding="utf-8") as f:
        return config_from_dict(json.load(f))


def tenant_from_profile(profile) -> TenantSpec | None:
    """Control-plane bridge: Profile + serving-tenant annotation ->
    TenantSpec (None when the profile isn't annotated). The annotation
    value may be empty / "true" (defaults) or a JSON object of spec
    fields; a malformed value raises — a silently-defaulted tenant
    whose operator thought they set a quota is worse than a loud
    reconcile error."""
    meta = getattr(profile, "metadata", profile)
    ann = getattr(meta, "annotations", None) or {}
    raw = ann.get(SERVING_TENANT_ANNOTATION)
    if raw is None:
        return None
    name = meta.name
    raw = raw.strip()
    if raw in ("", "true"):
        return TenantSpec(name=name)
    try:
        fields = json.loads(raw)
    except json.JSONDecodeError as e:
        raise ValueError(
            f"profile {name!r}: {SERVING_TENANT_ANNOTATION} is not "
            f"valid JSON: {e}") from e
    if not isinstance(fields, dict):
        raise ValueError(
            f"profile {name!r}: {SERVING_TENANT_ANNOTATION} must be a "
            f"JSON object, got {type(fields).__name__}")
    return spec_from_dict(name, fields)


def config_from_profiles(profiles,
                         default: TenantSpec | None = None) -> TenancyConfig:
    """Collect every annotated Profile into one TenancyConfig."""
    specs = []
    for p in profiles:
        spec = tenant_from_profile(p)
        if spec is not None and spec.name != DEFAULT_TENANT:
            specs.append(spec)
        elif spec is not None:
            default = default or spec
    return TenancyConfig(specs, default=default)
