"""Multi-tenant QoS for the serving data plane.

The control plane already has Profile-style multi-tenancy with
first-class TPU quota; this package carries that identity into the
data plane: per-tenant rate limits and KV shares (`ledger`), a
priority + weighted fair-share admission queue with preemption
(`scheduler`), and tenant specs loadable from a file or bridged from
Profile annotations (`config`).

Pure host-side Python — no jax, no aiohttp — so the fleet router and
the serving worker can both import it, and the math is unit-testable
with a fake clock.
"""

from __future__ import annotations

from kubeflow_tpu.tenancy.config import (
    DEFAULT_TENANT,
    PRIORITIES,
    SERVING_TENANT_ANNOTATION,
    TenancyConfig,
    TenantSpec,
    config_from_dict,
    config_from_profiles,
    load_config,
    tenant_from_profile,
)
from kubeflow_tpu.tenancy.ledger import (
    THROTTLE_REASONS,
    TenantLedger,
    Throttled,
    TokenBucket,
)
from kubeflow_tpu.tenancy.scheduler import FairShareQueue, ReqMeta

__all__ = [
    "DEFAULT_TENANT",
    "PRIORITIES",
    "SERVING_TENANT_ANNOTATION",
    "THROTTLE_REASONS",
    "FairShareQueue",
    "ReqMeta",
    "TenancyConfig",
    "TenantLedger",
    "TenantSpec",
    "Throttled",
    "TokenBucket",
    "config_from_dict",
    "config_from_profiles",
    "load_config",
    "tenant_from_profile",
]
