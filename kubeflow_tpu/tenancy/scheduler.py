"""Priority + weighted fair-share admission queue.

Drop-in replacement for the `ContinuousBatcher`'s FIFO `_pending`
deque (same `append` / `appendleft` / `popleft` / `__len__` surface)
that routes each request into a per-tenant sub-queue and picks the
next admission by:

1. strict priority class (`interactive` > `standard` > `batch`) —
   a lower class is served only when every higher class has nothing
   runnable;
2. within a class, weighted virtual time (start-time fair queuing):
   each pop charges its tenant `cost / weight` of virtual time and the
   tenant with the LOWEST virtual time goes next, so over time each
   tenant's completed-token share converges to its weight share;
3. a tenant whose generated-tokens/s bucket is in debt is not
   runnable — its queue is skipped (paced) until the ledger refills.

`popleft` returns None (instead of an item) when requests are queued
but every queued tenant is paced — the worker treats that as "nothing
admittable right now", not as empty.

Queue items are the batcher's pending tuples; this module only
touches two indices: `item[3]` (the request future — cancelled
requests don't count as waiting work) and `item[7]` (the `ReqMeta`
below, which the batcher attaches at enqueue).
"""

from __future__ import annotations

import collections

from kubeflow_tpu.tenancy.config import PRIORITIES, TenancyConfig
from kubeflow_tpu.tenancy.ledger import TenantLedger

_FUT, _META = 3, 7


class ReqMeta:
    """Per-request scheduling record riding the pending tuple (always
    present, tenant-blind or not — it also carries the enqueue
    timestamp the server's dynamic Retry-After is computed from)."""

    __slots__ = ("tenant", "priority", "weight", "cost", "t_enqueue",
                 "seq", "ns", "resume", "charged", "request_id",
                 "timeline", "restored")

    def __init__(self, tenant: str = "", priority: str = "standard",
                 weight: float = 1.0, cost: float = 1.0,
                 t_enqueue: float = 0.0, seq: int = 0, ns: str = "",
                 request_id: str = "", timeline=None):
        self.tenant = tenant
        self.priority = priority
        self.weight = weight
        self.cost = cost          # fair-share charge (≈ tokens asked)
        self.t_enqueue = t_enqueue
        self.seq = seq            # admission order; preemption evicts max
        self.ns = ns              # radix-cache namespace (prefix_isolation)
        self.resume = None        # preemption carry-over: {out, lps, max_new}
        self.charged = 0.0        # virtual time charged by the last pop
        self.request_id = request_id
        # obs.timeline.RequestTimeline — rides the meta so the record
        # survives preemption's re-enqueue round trip
        self.timeline = timeline
        # prompt cells whose radix hit came from spill-tier restores
        # (host->device copy, not a device-resident cache hit); the
        # batcher stamps it at admission so on_prefix can split the
        # reused count into `reused` vs `restored` metric sources
        self.restored = 0


class FairShareQueue:
    def __init__(self, config: TenancyConfig, ledger: TenantLedger):
        self.config = config
        self.ledger = ledger
        self._queues: dict[str, collections.deque] = {}
        self._vt: dict[str, float] = {}
        self._vclock = 0.0  # high-water virtual time across pops
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def _q(self, tenant: str) -> collections.deque:
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = collections.deque()
        if not q:
            # tenant going idle->busy: catch its virtual time up to the
            # high-water mark so idling doesn't bank credit it can
            # spend starving everyone later (standard start-time FQ)
            self._vt[tenant] = max(self._vt.get(tenant, 0.0),
                                   self._vclock)
        return q

    def append(self, item) -> None:
        self._q(item[_META].tenant).append(item)
        self._len += 1

    def appendleft(self, item) -> None:
        """Head re-insert — the deferral/preemption path. Refunds the
        virtual time the pop charged: a request the batcher could not
        actually admit must not cost its tenant fair share."""
        meta = item[_META]
        self._q(meta.tenant).appendleft(item)
        self._len += 1
        if meta.charged:
            self._vt[meta.tenant] -= meta.charged
            meta.charged = 0.0

    def popleft(self):
        """Next admission, or None when items exist but every queued
        tenant is token-paced. Raises IndexError when truly empty
        (deque parity)."""
        if self._len == 0:
            raise IndexError("pop from an empty FairShareQueue")
        for pri in PRIORITIES:
            best = None
            for tenant in sorted(self._queues):
                q = self._queues[tenant]
                if not q:
                    continue
                if self.config.resolve(tenant).priority != pri:
                    continue
                if self.ledger is not None \
                        and not self.ledger.runnable(tenant):
                    continue
                vt = self._vt.get(tenant, 0.0)
                if best is None or vt < best[1]:
                    best = (tenant, vt)
            if best is None:
                continue
            tenant, vt = best
            item = self._queues[tenant].popleft()
            self._len -= 1
            meta = item[_META]
            charge = max(1.0, float(meta.cost)) / max(1e-9, meta.weight)
            self._vt[tenant] = vt + charge
            meta.charged = charge
            self._vclock = max(self._vclock, self._vt[tenant])
            return item
        return None

    def has_waiting(self, priority: str) -> bool:
        """Any live (non-cancelled) request of this class queued? The
        batcher's preemption trigger."""
        for tenant, q in self._queues.items():
            if not q:
                continue
            if self.config.resolve(tenant).priority != priority:
                continue
            if any(not it[_FUT].done() for it in q):
                return True
        return False

    def pacing_delay(self) -> float:
        """Shortest token-debt refill among queued tenants (0.0 when
        someone is runnable) — how long the worker may nap when
        popleft returned None."""
        best = None
        for tenant, q in self._queues.items():
            if not q:
                continue
            d = (self.ledger.pacing_delay(tenant)
                 if self.ledger is not None else 0.0)
            if best is None or d < best:
                best = d
        return best or 0.0

    def depths(self) -> dict[str, int]:
        """Queue depth per tenant, zero-seeded for every configured
        tenant (the `serving_tenant_queue_depth` gauge)."""
        out = dict.fromkeys(self.config.names(), 0)
        for tenant, q in self._queues.items():
            out[tenant] = len(q)
        return out

    def items(self) -> list:
        """Non-destructive snapshot of every queued item, in tenant
        order (the checkpoint/migration export paths read this; pops
        and pacing state are untouched)."""
        out = []
        for tenant in sorted(self._queues):
            out.extend(self._queues[tenant])
        return out

    def drain_all(self) -> list:
        """Remove and return every queued item (shutdown path)."""
        items = []
        for tenant in sorted(self._queues):
            items.extend(self._queues[tenant])
            self._queues[tenant].clear()
        self._len = 0
        return items
