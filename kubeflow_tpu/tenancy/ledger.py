"""Per-tenant ledger: token buckets + live usage accounting.

Two buckets per tenant: requests/s (enforced at the admission door —
an empty bucket is a `Throttled`, HTTP 429 with a computed
Retry-After) and generated-tokens/s (enforced by PACING, not
rejection: `charge_tokens` may drive the bucket negative as tokens
stream out, and the fair-share scheduler simply stops popping for a
tenant in debt until it refills — mid-generation rejection isn't a
thing). The clock is injectable so refill math is unit-testable
without sleeping.

The ledger is also the single source of truth the serving metrics
render from (`serving_tenant_*` — a scrape-time collector reads
`stats()`), which is why every counter lives here instead of being
scattered through the batcher.
"""

from __future__ import annotations

import time

from kubeflow_tpu.tenancy.config import TenancyConfig

# Throttle reasons, zero-seeded into serving_tenant_throttled_total:
# `rate` = request bucket empty at the door, `kv_quota` = admission
# deferred because the tenant's concurrent KV-block share is spent.
THROTTLE_REASONS = ("rate", "kv_quota")


class Throttled(RuntimeError):
    """Tenant over its rate limit — shed load (HTTP 429). Carries the
    bucket's refill time so the 429 can say WHEN to come back instead
    of a hardcoded Retry-After."""

    def __init__(self, tenant: str, reason: str, retry_after: float):
        super().__init__(
            f"tenant {tenant!r} throttled ({reason}); "
            f"retry in {retry_after:.2f}s")
        self.tenant = tenant
        self.reason = reason
        self.retry_after = retry_after


class TokenBucket:
    """Classic token bucket. rate <= 0 disables the limit entirely;
    burst <= 0 defaults to max(1, rate) (one second of headroom)."""

    __slots__ = ("rate", "burst", "level", "_t", "_clock")

    def __init__(self, rate: float, burst: float = 0.0, *,
                 clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else max(1.0, self.rate)
        self.level = self.burst
        self._clock = clock
        self._t = clock()

    @property
    def unlimited(self) -> bool:
        return self.rate <= 0

    def _refill(self) -> None:
        now = self._clock()
        if now > self._t:
            self.level = min(self.burst,
                             self.level + (now - self._t) * self.rate)
        self._t = now

    def try_take(self, n: float = 1.0) -> bool:
        """Take n tokens iff available now (the admission door)."""
        if self.unlimited:
            return True
        self._refill()
        if self.level >= n:
            self.level -= n
            return True
        return False

    def take(self, n: float = 1.0) -> None:
        """Unconditional charge; the level may go NEGATIVE (debt).
        Used for generated tokens, which exist whether or not the
        tenant had budget — debt pauses the tenant instead."""
        if self.unlimited:
            return
        self._refill()
        self.level -= n

    def delay_until(self, n: float = 1.0) -> float:
        """Seconds until n tokens are available (0.0 = now)."""
        if self.unlimited:
            return 0.0
        self._refill()
        return max(0.0, (n - self.level) / self.rate)

    def debt_delay(self) -> float:
        """Seconds until the bucket is back to >= 0 (0.0 = solvent)."""
        if self.unlimited:
            return 0.0
        self._refill()
        return max(0.0, -self.level / self.rate)


class TenantUsage:
    """Live + cumulative accounting for one tenant."""

    __slots__ = ("admitted", "completed", "tokens", "slots_held",
                 "blocks_held", "preempted", "throttled")

    def __init__(self):
        self.admitted = 0      # requests past the rate-limit door
        self.completed = 0     # requests finished (any way)
        self.tokens = 0        # tokens generated, cumulative
        self.slots_held = 0    # decode slots held right now
        self.blocks_held = 0   # exclusively-owned KV blocks right now
        self.preempted = 0     # times a decode was evicted, cumulative
        self.throttled = dict.fromkeys(THROTTLE_REASONS, 0)

    def as_dict(self) -> dict:
        return {
            "admitted": self.admitted,
            "completed": self.completed,
            "tokens": self.tokens,
            "slots_held": self.slots_held,
            "blocks_held": self.blocks_held,
            "preempted": self.preempted,
            "throttled": dict(self.throttled),
        }


class TenantLedger:
    """Rate limits + usage for every tenant in a TenancyConfig. All
    identities are RESOLVED through the config first, so the key space
    is bounded by configuration (unknown names account as `default`)."""

    def __init__(self, config: TenancyConfig, *, clock=time.monotonic):
        self.config = config
        self._clock = clock
        self._req: dict[str, TokenBucket] = {}
        self._tok: dict[str, TokenBucket] = {}
        # zero-seed: every configured tenant has a row before traffic,
        # so /metrics exposes the full series set from the first scrape
        self._usage: dict[str, TenantUsage] = {
            name: TenantUsage() for name in config.names()}

    def _key(self, tenant: str) -> str:
        return self.config.resolve(tenant).name

    def usage(self, tenant: str) -> TenantUsage:
        return self._usage.setdefault(self._key(tenant), TenantUsage())

    def _request_bucket(self, tenant: str) -> TokenBucket:
        key = self._key(tenant)
        b = self._req.get(key)
        if b is None:
            spec = self.config.resolve(key)
            b = self._req[key] = TokenBucket(
                spec.requests_per_s, spec.request_burst,
                clock=self._clock)
        return b

    def _token_bucket(self, tenant: str) -> TokenBucket:
        key = self._key(tenant)
        b = self._tok.get(key)
        if b is None:
            spec = self.config.resolve(key)
            b = self._tok[key] = TokenBucket(
                spec.tokens_per_s, spec.token_burst, clock=self._clock)
        return b

    # -- admission door ----------------------------------------------------

    def check_request(self, tenant: str) -> None:
        """Charge one request against the tenant's bucket, or raise
        Throttled with the refill time. Call BEFORE spending anything
        on the request."""
        key = self._key(tenant)
        b = self._request_bucket(key)
        if not b.try_take(1.0):
            self.note_throttled(key, "rate")
            raise Throttled(key, "rate", b.delay_until(1.0))
        self.usage(key).admitted += 1

    # -- pacing (generated tokens/s) ---------------------------------------

    def charge_tokens(self, tenant: str, n: int = 1) -> None:
        u = self.usage(tenant)
        u.tokens += n
        self._token_bucket(tenant).take(float(n))

    def runnable(self, tenant: str) -> bool:
        """False while the tenant's token bucket is in debt — the
        scheduler skips its queue until the debt refills."""
        return self._token_bucket(tenant).debt_delay() == 0.0

    def pacing_delay(self, tenant: str) -> float:
        return self._token_bucket(tenant).debt_delay()

    # -- KV share ----------------------------------------------------------

    def block_limit(self, tenant: str, capacity: int) -> int | None:
        """Max pool blocks this tenant may hold concurrently, or None
        when uncapped."""
        share = self.config.resolve(tenant).kv_block_share
        if share >= 1.0:
            return None
        return max(1, int(share * capacity))

    def blocks_held(self, tenant: str) -> int:
        return self.usage(tenant).blocks_held

    # -- bookkeeping hooks (the batcher calls these) -----------------------

    def note_slot_taken(self, tenant: str, blocks: int) -> None:
        u = self.usage(tenant)
        u.slots_held += 1
        u.blocks_held += blocks

    def note_slot_released(self, tenant: str, blocks: int) -> None:
        u = self.usage(tenant)
        u.slots_held -= 1
        u.blocks_held -= blocks

    def note_completed(self, tenant: str) -> None:
        self.usage(tenant).completed += 1

    def note_preempted(self, tenant: str) -> None:
        self.usage(tenant).preempted += 1

    def note_throttled(self, tenant: str, reason: str) -> None:
        u = self.usage(tenant)
        u.throttled[reason] = u.throttled.get(reason, 0) + 1

    def stats(self) -> dict[str, dict]:
        return {name: u.as_dict() for name, u in self._usage.items()}
