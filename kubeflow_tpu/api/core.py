"""Core resource model: metadata envelope + workload types.

Design: every resource is a dataclass subclassing `Resource` with
`metadata: ObjectMeta` plus kind-specific spec/status dataclasses.
Serialization is structural (`to_dict`/`resource_from_dict`) so the REST
layer, the store, and tests all speak plain dicts — the same role the
k8s API machinery plays for the reference's Go structs
(e.g. notebook-controller/api/v1beta1/notebook_types.go:69-75).

These are *our* workload types, not k8s clones: just enough surface for
the controllers' semantics (env/volume merge, gang replicas, routing),
with TPU fields first-class where k8s would use annotations.
"""

from __future__ import annotations

import copy
import dataclasses
import time
import typing
from dataclasses import dataclass, field
from typing import Any, ClassVar

API_VERSION = "kubeflow-tpu.dev/v1"


def _now() -> float:
    return time.time()


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    creation_timestamp: float = 0.0
    deletion_timestamp: float | None = None
    owner_references: list[OwnerReference] = field(default_factory=list)
    finalizers: list[str] = field(default_factory=list)


@dataclass
class OwnerReference:
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = True


# ---------------------------------------------------------------------------
# Pod building blocks (consumed by the webhook merge engine — the analog of
# admission-webhook/main.go:153-364's env/volume/toleration merging).
# ---------------------------------------------------------------------------


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    sub_path: str = ""
    read_only: bool = False


@dataclass
class Volume:
    name: str = ""
    # Exactly one of the sources is typically set.
    pvc_name: str = ""          # persistent claim
    empty_dir: bool = False
    config_map: str = ""
    secret: str = ""
    size_limit: str = ""        # for empty_dir (e.g. shm)


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"
    value: str = ""
    effect: str = ""


@dataclass
class ResourceRequirements:
    requests: dict[str, str] = field(default_factory=dict)
    limits: dict[str, str] = field(default_factory=dict)


@dataclass
class Probe:
    path: str = ""
    port: int = 0
    initial_delay_seconds: int = 0
    period_seconds: int = 10


@dataclass
class Container:
    name: str = ""
    image: str = ""
    image_pull_policy: str = ""   # "" | Always | IfNotPresent | Never
    command: list[str] = field(default_factory=list)
    args: list[str] = field(default_factory=list)
    env: list[EnvVar] = field(default_factory=list)
    ports: list[int] = field(default_factory=list)
    volume_mounts: list[VolumeMount] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    working_dir: str = ""
    liveness_probe: Probe | None = None
    readiness_probe: Probe | None = None


@dataclass
class NodeSelectorTerm:
    key: str = ""
    values: list[str] = field(default_factory=list)


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    volumes: list[Volume] = field(default_factory=list)
    tolerations: list[Toleration] = field(default_factory=list)
    service_account: str = ""
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity_terms: list[NodeSelectorTerm] = field(default_factory=list)
    scheduler_name: str = ""
    fs_group: int | None = None
    hostname: str = ""
    subdomain: str = ""


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


# ---------------------------------------------------------------------------
# Resource envelope + registry
# ---------------------------------------------------------------------------

_KIND_REGISTRY: dict[str, type] = {}


@dataclass
class Resource:
    KIND: ClassVar[str] = ""
    NAMESPACED: ClassVar[bool] = True

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.KIND:
            _KIND_REGISTRY[cls.KIND] = cls

    @property
    def kind(self) -> str:
        return type(self).KIND

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.kind, self.metadata.namespace, self.metadata.name)

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["apiVersion"] = API_VERSION
        d["kind"] = self.kind
        return d

    def clone(self):
        # The store clones on EVERY read/write boundary (apiserver wire
        # semantics), so this is the control plane's hottest function:
        # the reconcile-fanout loadtest spent 60%+ of its wall time in
        # copy.deepcopy (memo bookkeeping, reduce-protocol dispatch).
        # Resources are plain dataclass/list/dict/scalar trees, so a
        # direct structural copy is ~4x faster and semantically
        # identical for them.
        return _structural_copy(self)


def _structural_copy(x):
    t = type(x)
    if t in (str, int, float, bool, type(None)):
        return x
    if t is list:
        return [_structural_copy(v) for v in x]
    if t is dict:
        return {k: _structural_copy(v) for k, v in x.items()}
    if t is tuple:
        return tuple(_structural_copy(v) for v in x)
    if dataclasses.is_dataclass(x):
        new = t.__new__(t)
        d = new.__dict__
        for k, v in x.__dict__.items():
            d[k] = _structural_copy(v)
        return new
    # Anything exotic (shouldn't appear in a Resource tree) falls back
    # to the general machinery rather than sharing a reference.
    return copy.deepcopy(x)


def _build(cls, data):
    """Recursively build a dataclass from a plain dict (tolerant: unknown
    keys are ignored; missing keys take defaults)."""
    if data is None:
        return None
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        v = data[f.name]
        t = hints.get(f.name, Any)
        kwargs[f.name] = _coerce(t, v)
    return cls(**kwargs)


def _coerce(t, v):
    origin = typing.get_origin(t)
    if origin in (typing.Union, getattr(__import__("types"), "UnionType", None)):
        args = [a for a in typing.get_args(t) if a is not type(None)]
        if v is None:
            return None
        return _coerce(args[0], v)
    if dataclasses.is_dataclass(t) and isinstance(v, dict):
        return _build(t, v)
    if origin is list and isinstance(v, list):
        (elem,) = typing.get_args(t)
        return [_coerce(elem, x) for x in v]
    if origin is dict and isinstance(v, dict):
        return dict(v)
    return v


def resource_from_dict(data: dict[str, Any]) -> Resource:
    kind = data.get("kind", "")
    cls = _KIND_REGISTRY.get(kind)
    if cls is None:
        raise ValueError(f"unknown kind {kind!r}")
    payload = {k: v for k, v in data.items() if k not in ("apiVersion", "kind")}
    return _build(cls, payload)


def registered_kinds() -> dict[str, type]:
    return dict(_KIND_REGISTRY)


# ---------------------------------------------------------------------------
# Workload resources the controllers own (reference L2 outputs)
# ---------------------------------------------------------------------------


@dataclass
class Pod(Resource):
    KIND: ClassVar[str] = "Pod"
    spec: PodSpec = field(default_factory=PodSpec)
    # status
    phase: str = "Pending"   # Pending/Running/Succeeded/Failed
    ready: bool = False
    host_ip: str = ""
    pod_ip: str = ""
    conditions: list[dict] = field(default_factory=list)


@dataclass
class StatefulSetSpec:
    replicas: int = 1
    service_name: str = ""
    selector: dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    # Gang semantics: all-or-nothing pod creation for TPU slices
    # (reference never needed this — single-pod notebooks; SURVEY.md §7
    # "hard parts" (a)).
    gang: bool = False


@dataclass
class StatefulSet(Resource):
    KIND: ClassVar[str] = "StatefulSet"
    spec: StatefulSetSpec = field(default_factory=StatefulSetSpec)
    ready_replicas: int = 0


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: int = 0


@dataclass
class ServiceSpec:
    selector: dict[str, str] = field(default_factory=dict)
    ports: list[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""
    headless: bool = False


@dataclass
class Service(Resource):
    KIND: ClassVar[str] = "Service"
    spec: ServiceSpec = field(default_factory=ServiceSpec)


@dataclass
class HTTPRoute:
    prefix: str = ""
    rewrite: str = ""
    destination_host: str = ""
    destination_port: int = 0
    headers: dict[str, str] = field(default_factory=dict)
    timeout: str = ""


@dataclass
class VirtualServiceSpec:
    gateways: list[str] = field(default_factory=list)
    hosts: list[str] = field(default_factory=list)
    http: list[HTTPRoute] = field(default_factory=list)


@dataclass
class VirtualService(Resource):
    KIND: ClassVar[str] = "VirtualService"
    spec: VirtualServiceSpec = field(default_factory=VirtualServiceSpec)


@dataclass
class DeploymentSpec:
    replicas: int = 1
    selector: dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class Deployment(Resource):
    KIND: ClassVar[str] = "Deployment"
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    ready_replicas: int = 0
    conditions: list[dict] = field(default_factory=list)


@dataclass
class PersistentVolumeClaim(Resource):
    KIND: ClassVar[str] = "PersistentVolumeClaim"
    storage: str = "5Gi"
    access_modes: list[str] = field(default_factory=lambda: ["ReadWriteOnce"])
    storage_class: str = ""
    phase: str = "Bound"  # hermetic cluster binds immediately


@dataclass
class Event(Resource):
    KIND: ClassVar[str] = "Event"
    involved_kind: str = ""
    involved_name: str = ""
    type: str = "Normal"   # Normal | Warning
    reason: str = ""
    message: str = ""
    timestamp: float = field(default_factory=_now)
    # Duplicate aggregation (k8s event count semantics): repeats of the
    # same (involved, type, reason, message) bump count/last_timestamp
    # instead of growing the store.
    count: int = 1
    last_timestamp: float = 0.0


@dataclass
class Namespace(Resource):
    KIND: ClassVar[str] = "Namespace"
    NAMESPACED: ClassVar[bool] = False
    phase: str = "Active"


@dataclass
class ServiceAccount(Resource):
    KIND: ClassVar[str] = "ServiceAccount"
    # Populated asynchronously by the platform (the reference waits on this
    # before unlocking notebook start, odh notebook_controller.go:94-122).
    image_pull_secrets: list[str] = field(default_factory=list)


@dataclass
class RoleBinding(Resource):
    KIND: ClassVar[str] = "RoleBinding"
    role: str = ""            # cluster role name, e.g. "kubeflow-tpu-edit"
    subjects: list[str] = field(default_factory=list)  # user ids


@dataclass
class AuthorizationPolicy(Resource):
    KIND: ClassVar[str] = "AuthorizationPolicy"
    # principals/headers allowed; paths optionally restricted
    allow_users: list[str] = field(default_factory=list)
    allow_namespaces: list[str] = field(default_factory=list)
    allow_paths: list[str] = field(default_factory=list)


@dataclass
class ResourceQuota(Resource):
    KIND: ClassVar[str] = "ResourceQuota"
    hard: dict[str, str] = field(default_factory=dict)  # incl. "tpu/chips"


@dataclass
class Route(Resource):
    """Edge ingress route (OpenShift Route equivalent; on GKE this maps to
    a gateway HTTPRoute). Exposes a Service at a cluster-external host."""

    KIND: ClassVar[str] = "Route"
    host: str = ""              # assigned by the platform when empty
    to_service: str = ""
    target_port: str = ""       # named service port
    tls_termination: str = ""   # "" | "edge" | "reencrypt"
    redirect_insecure: bool = True


@dataclass
class NetworkPolicy(Resource):
    KIND: ClassVar[str] = "NetworkPolicy"
    allow_from_namespaces: list[str] = field(default_factory=list)
    allow_ports: list[int] = field(default_factory=list)


@dataclass
class ConfigMap(Resource):
    KIND: ClassVar[str] = "ConfigMap"
    data: dict[str, str] = field(default_factory=dict)


@dataclass
class Secret(Resource):
    KIND: ClassVar[str] = "Secret"
    data: dict[str, str] = field(default_factory=dict)
