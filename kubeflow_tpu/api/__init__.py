"""Typed resource model (reference L1: CRD type definitions).

The reference defines its types as Go structs registered into a k8s
scheme (notebook_types.go, profile_types.go, poddefault_types.go,
tensorboard_types.go). Here resources are Python dataclasses with a
uniform envelope (apiVersion/kind/metadata/spec/status) and dict
round-tripping, served by the kubeflow_tpu.controlplane store.
"""

from kubeflow_tpu.api.core import (
    Container,
    EnvVar,
    Event,
    Namespace,
    ObjectMeta,
    OwnerReference,
    PersistentVolumeClaim,
    Pod,
    PodSpec,
    Resource,
    RoleBinding,
    Service,
    ServiceAccount,
    ServicePort,
    StatefulSet,
    Toleration,
    VirtualService,
    Volume,
    VolumeMount,
    resource_from_dict,
)
from kubeflow_tpu.api.crds import (
    ModelServer,
    ModelServerSpec,
    Notebook,
    NotebookSpec,
    NotebookStatus,
    Profile,
    ProfileSpec,
    Tensorboard,
    TensorboardSpec,
    TpuPodDefault,
    TpuPodDefaultSpec,
    TpuSpec,
)
