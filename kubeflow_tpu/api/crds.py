"""Platform CRDs: Notebook, Profile, TpuPodDefault, Tensorboard.

TPU-first redesign of the reference CRDs:
- `Notebook` (ref: notebook-controller/api/v1beta1/notebook_types.go:69-75)
  gains a first-class `tpu` block (slice topology, generation) instead of
  GPU vendor annotations; the reconciler derives gang replica count from
  the topology (one pod per TPU VM host).
- `Profile` (ref: profile-controller/api/v1/profile_types.go:63-69) quota
  includes TPU chips.
- `TpuPodDefault` (ref: admission-webhook/pkg/apis/settings/v1alpha1/
  poddefault_types.go:27-78) keeps the label-selected merge semantics and
  adds `tpu_env: bool` to opt a pod into automatic TPU_WORKER_* injection.
- `Tensorboard` (ref: tensorboard-controller/api/v1alpha1/
  tensorboard_types.go:57-63) keeps logspath dispatch (pvc:// | gs://).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import ClassVar

from kubeflow_tpu.api.core import (
    PodTemplateSpec,
    Resource,
    Toleration,
    Volume,
    VolumeMount,
    EnvVar,
)


# ---------------------------------------------------------------------------
# Notebook
# ---------------------------------------------------------------------------


@dataclass
class TpuSpec:
    """TPU attachment for a workload. Empty topology = CPU-only pod."""

    topology: str = ""          # e.g. "v5e-16" (kubeflow_tpu.parallel.mesh)
    # Parallelism layout hint injected as KFTPU_MESH for in-pod JAX.
    mesh: str = ""              # e.g. "data=1,fsdp=16,tensor=1"
    # Multi-slice job: N whole slices of `topology` gang-scheduled
    # together; the webhook injects MEGASCALE_* env so JAX builds the
    # hybrid (dcn x ici) mesh and DP rides DCN across slices.
    num_slices: int = 1
    reserved: bool = False      # use reserved capacity


@dataclass
class NotebookSpec:
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    tpu: TpuSpec = field(default_factory=TpuSpec)


@dataclass
class NotebookCondition:
    type: str = ""
    reason: str = ""
    message: str = ""
    last_probe_time: float = 0.0


@dataclass
class NotebookStatus:
    ready_replicas: int = 0
    container_state: str = ""   # waiting | running | terminated
    conditions: list[NotebookCondition] = field(default_factory=list)


@dataclass
class Notebook(Resource):
    KIND: ClassVar[str] = "Notebook"
    spec: NotebookSpec = field(default_factory=NotebookSpec)
    status: NotebookStatus = field(default_factory=NotebookStatus)


# Annotations shared with the reference's semantics (culler / stop):
STOP_ANNOTATION = "kubeflow-tpu.dev/stopped"           # ref culler.go:36-40
LAST_ACTIVITY_ANNOTATION = "kubeflow-tpu.dev/last-activity"
CULLING_DISABLED_ANNOTATION = "kubeflow-tpu.dev/culling-disabled"
# Webhook bookkeeping (ref admission-webhook/main.go:424-426 stamps
# poddefault.admission.kubeflow.org/poddefault-<name>=<rv>):
PODDEFAULT_APPLIED_PREFIX = "tpupoddefault.kubeflow-tpu.dev/"
WEBHOOK_EXCLUDE_ANNOTATION = "kubeflow-tpu.dev/webhook-exclude"


# ---------------------------------------------------------------------------
# Profile (multi-tenancy)
# ---------------------------------------------------------------------------


@dataclass
class ProfilePluginSpec:
    """Per-profile cloud-identity plugin (ref GetPluginSpec,
    profile_controller.go:643-675: plugins are part of the Profile CR)."""

    kind: str = ""                        # "WorkloadIdentity" | "IamForServiceAccount"
    options: dict[str, str] = field(default_factory=dict)


@dataclass
class ProfileSpec:
    owner: str = ""                       # user id (email)
    resource_quota: dict[str, str] = field(default_factory=dict)
    # e.g. {"cpu": "32", "memory": "128Gi", "tpu/v5e-chips": "16"}
    plugins: list[ProfilePluginSpec] = field(default_factory=list)


@dataclass
class ProfileStatus:
    phase: str = ""  # "" | Ready | Failed
    message: str = ""


@dataclass
class Profile(Resource):
    KIND: ClassVar[str] = "Profile"
    NAMESPACED: ClassVar[bool] = False    # cluster-scoped, owns a namespace
    spec: ProfileSpec = field(default_factory=ProfileSpec)
    status: ProfileStatus = field(default_factory=ProfileStatus)


PROFILE_FINALIZER = "profile.kubeflow-tpu.dev/cleanup"


# ---------------------------------------------------------------------------
# TpuPodDefault (PodDefault, TPU-first)
# ---------------------------------------------------------------------------


@dataclass
class TpuPodDefaultSpec:
    # label selector choosing which pods this applies to
    selector: dict[str, str] = field(default_factory=dict)
    desc: str = ""
    env: list[EnvVar] = field(default_factory=list)
    volumes: list[Volume] = field(default_factory=list)
    volume_mounts: list[VolumeMount] = field(default_factory=list)
    tolerations: list[Toleration] = field(default_factory=list)
    annotations: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    service_account: str = ""
    command: list[str] = field(default_factory=list)
    args: list[str] = field(default_factory=list)
    # TPU-native: inject TPU_WORKER_ID/TPU_WORKER_HOSTNAMES/coordinator
    # env derived from the pod's gang position (the NCCL-free bootstrap).
    tpu_env: bool = False


@dataclass
class TpuPodDefault(Resource):
    KIND: ClassVar[str] = "TpuPodDefault"
    spec: TpuPodDefaultSpec = field(default_factory=TpuPodDefaultSpec)


# ---------------------------------------------------------------------------
# Tensorboard
# ---------------------------------------------------------------------------


@dataclass
class TensorboardSpec:
    logspath: str = ""   # "pvc://name/subpath" | "gs://bucket/path"


@dataclass
class TensorboardStatus:
    ready: bool = False
    conditions: list[dict] = field(default_factory=list)


@dataclass
class Tensorboard(Resource):
    KIND: ClassVar[str] = "Tensorboard"
    spec: TensorboardSpec = field(default_factory=TensorboardSpec)
    status: TensorboardStatus = field(default_factory=TensorboardStatus)


@dataclass
class ModelServerSpec:
    """Serve a model over REST on a TPU slice (the KServe-shaped gap:
    the reference's serving story was the removed TF-Serving component
    fronted by Service/VirtualService; here the pod runs
    `python -m kubeflow_tpu.serving`)."""

    model: str = "llama-tiny"    # serving.__main__ registry name
    # "pvc://name/subpath" (train.Checkpointer dir on a PVC),
    # "gs://bucket/path", or "" = random init (smoke/dev)
    checkpoint: str = ""
    # Fleet sizing (ISSUE 3): `replicas` is the baseline (and the
    # autoscale floor); `max_replicas > 0` enables annotation-driven
    # autoscaling — the fleet router's recommendation is written to
    # the kubeflow-tpu.dev/desired-replicas annotation and the
    # controller clamps it into [replicas, max_replicas], draining
    # excess pods before deleting them on scale-down.
    replicas: int = 1
    max_replicas: int = 0        # 0 = autoscale off
    # Disaggregated serving (ISSUE 12): when both are > 0 the fleet
    # splits into a prefill pool and a decode pool of these sizes
    # (replacing the symmetric `replicas` count; requires
    # `continuous`). Prefill pods run with zero decode pressure, fill
    # paged KV blocks, and ship them to the decode pool through the
    # router's handoff; the pools scale independently off the
    # phase-seconds split (`/fleet/autoscale?pools=1`).
    prefill_replicas: int = 0    # 0 = symmetric (no disaggregation)
    decode_replicas: int = 0
    max_len: int = 1024
    continuous: bool = True
    warmup: bool = True
    max_batch: int = 8
    prefill_chunk: int = 0       # 0 = off
    quant: str = ""              # "" | int8
    # "auto" = tokenizer.json beside the checkpoint when present (the
    # tools/prepare_data.py output), "none" = byte fallback forced,
    # else an explicit tokenizer file path/URL for text mode
    tokenizer: str = "auto"
    # Rollout plane (ISSUE 18): the model version label the pods BOOT
    # with ("" = unversioned). Live rollouts do not go through the
    # CRD — the RolloutManager reloads running replicas in place — but
    # the kubeflow-tpu.dev/model-version annotation (which overrides
    # this field) lets whatever consumes /fleet/versions pin the
    # version new/restarted pods come up on, so a pod restart during a
    # completed rollout does not resurrect the old weights' label.
    model_version: str = ""
    tpu: TpuSpec = field(default_factory=TpuSpec)


@dataclass
class ModelServerStatus:
    ready: bool = False
    url: str = ""
    conditions: list[dict] = field(default_factory=list)


@dataclass
class ModelServer(Resource):
    KIND: ClassVar[str] = "ModelServer"
    spec: ModelServerSpec = field(default_factory=ModelServerSpec)
    status: ModelServerStatus = field(default_factory=ModelServerStatus)


# ---------------------------------------------------------------------------
# HPO: Experiment / Trial (Katib StudyJob equivalent — the reference only
# smoke-tests Katib from outside, testing/katib_studyjob_test.py; the CRD
# itself lives in the separate katib repo, so this is a green-field design)
# ---------------------------------------------------------------------------


@dataclass
class ParameterSpec:
    """One search dimension. type: double | int | categorical."""

    name: str = ""
    type: str = "double"
    min: float = 0.0
    max: float = 0.0
    log: bool = False                      # double only
    values: list[str] = field(default_factory=list)  # categorical only


@dataclass
class ObjectiveSpec:
    metric: str = "loss"
    goal: str = "minimize"                 # minimize | maximize


@dataclass
class EarlyStoppingSpec:
    """Katib-style early stopping. `medianstop`: a running trial whose
    best objective by reported step s is worse than the MEDIAN of the
    completed trials' best-by-s is stopped (its compute freed for the
    next suggestion). Arms only once `min_trials` completed trials
    have reported intermediate metrics, and never before a trial's
    `start_step`-th report."""

    algorithm: str = ""                    # "" (off) | medianstop
    min_trials: int = 3
    start_step: int = 1


@dataclass
class ExperimentSpec:
    objective: ObjectiveSpec = field(default_factory=ObjectiveSpec)
    algorithm: str = "random"              # random | grid
    seed: int = 0
    parameters: list[ParameterSpec] = field(default_factory=list)
    max_trials: int = 10
    parallel_trials: int = 2
    early_stopping: EarlyStoppingSpec = field(
        default_factory=EarlyStoppingSpec)
    # Pod template for each trial; hyperparameters are injected as
    # KFTPU_HP_<NAME> env vars and TPU env rides the normal webhook path.
    trial_template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    tpu: TpuSpec = field(default_factory=TpuSpec)


@dataclass
class ExperimentStatus:
    phase: str = ""       # "" | Running | Succeeded | Failed
    trials_created: int = 0
    trials_succeeded: int = 0
    trials_failed: int = 0
    trials_early_stopped: int = 0
    best_trial: str = ""
    best_value: float | None = None
    best_assignment: dict[str, str] = field(default_factory=dict)
    message: str = ""


@dataclass
class Experiment(Resource):
    KIND: ClassVar[str] = "Experiment"
    spec: ExperimentSpec = field(default_factory=ExperimentSpec)
    status: ExperimentStatus = field(default_factory=ExperimentStatus)


@dataclass
class TrialSpec:
    experiment: str = ""
    assignment: dict[str, str] = field(default_factory=dict)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    tpu: TpuSpec = field(default_factory=TpuSpec)
    objective_metric: str = "loss"


@dataclass
class TrialStatus:
    phase: str = ""       # "" | Running | Succeeded | Failed | EarlyStopped
    value: float | None = None
    message: str = ""
    # [step, value] pairs mirrored from the pod's intermediate-metrics
    # annotation; the median stopping rule reads these.
    intermediates: list[list[float]] = field(default_factory=list)


@dataclass
class Trial(Resource):
    KIND: ClassVar[str] = "Trial"
    spec: TrialSpec = field(default_factory=TrialSpec)
    status: TrialStatus = field(default_factory=TrialStatus)


# Trial pods report their objective via this annotation (written by the
# in-pod metric reporter; the trial controller mirrors it into status).
TRIAL_METRIC_ANNOTATION = "kubeflow-tpu.dev/metric-value"
# Progressive [step, value] JSON reported DURING the run (same writer);
# feeds the median stopping rule.
TRIAL_INTERMEDIATE_ANNOTATION = "kubeflow-tpu.dev/intermediate-metrics"
TRIAL_LABEL = "trial-name"
EXPERIMENT_LABEL = "experiment-name"
