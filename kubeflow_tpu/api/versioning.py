"""Multi-version CRD serving: wire-level conversion, hub-and-spoke.

The reference serves Notebook at v1alpha1/v1beta1/v1 with conversion
functions between them (`/root/reference/components/notebook-controller/
api/v1beta1/notebook_conversion.go`, storage v1beta1 per
notebook_types.go markers) so old clients keep working across upgrades.
Same capability here, shaped the way k8s conversion actually works:
converters operate on the SERIALIZED form (conversion webhooks receive
JSON, not typed structs), every version converts through the hub
(the storage version), and fields a down-level version cannot represent
ride annotations so the round-trip is lossless — the k8s
multi-version round-trippability rule.

Served Notebook versions:
  v1alpha1 — legacy flat shape: spec.accelerator ("v5e-16") +
             spec.mesh, predating the tpu block.
  v1beta1  — tpu block {topology, mesh}, predating multi-slice.
  v1       — storage (the in-code dataclasses): tpu block with
             num_slices/reserved.

`resource_from_versioned_dict` is the store-facing entry: it accepts a
dict in ANY served version and up-converts before building the typed
resource; `to_versioned_dict` serves a stored object at the version a
client asked for.
"""

from __future__ import annotations

import copy
from typing import Any, Callable

from kubeflow_tpu.api import core

GROUP = "kubeflow-tpu.dev"
STORAGE_VERSION = "v1"
SERVED_VERSIONS: dict[str, tuple[str, ...]] = {
    "Notebook": ("v1alpha1", "v1beta1", "v1"),
    "Profile": ("v1beta1", "v1"),
}

# Unrepresentable-field stash (k8s round-trip discipline): conversion TO
# a down-level version records what it had to drop; conversion back to
# the hub restores it.
NUM_SLICES_ANNOTATION = f"{GROUP}/conversion.num-slices"
RESERVED_ANNOTATION = f"{GROUP}/conversion.reserved"

Converter = Callable[[dict], dict]
# (kind, from_version) -> to-hub converter; (kind, to_version) -> from-hub
_TO_HUB: dict[tuple[str, str], Converter] = {}
_FROM_HUB: dict[tuple[str, str], Converter] = {}


def register_conversion(kind: str, version: str, *, to_hub: Converter,
                        from_hub: Converter) -> None:
    _TO_HUB[(kind, version)] = to_hub
    _FROM_HUB[(kind, version)] = from_hub


def parse_api_version(api_version: str) -> str:
    group, _, version = api_version.partition("/")
    if version == "":           # bare "v1" tolerated
        return group
    if group != GROUP:
        raise ValueError(f"unknown API group {group!r} (want {GROUP})")
    return version


def convert_dict(data: dict[str, Any], to_version: str) -> dict[str, Any]:
    """Convert a serialized resource between served versions (via hub)."""
    kind = data.get("kind", "")
    served = SERVED_VERSIONS.get(kind)
    from_version = parse_api_version(data.get("apiVersion",
                                              f"{GROUP}/{STORAGE_VERSION}"))
    if served is None:
        # Single-version kind: only the storage version exists.
        if from_version != STORAGE_VERSION or to_version != STORAGE_VERSION:
            raise ValueError(
                f"kind {kind!r} is served at {STORAGE_VERSION} only")
        return data
    for v in (from_version, to_version):
        if v not in served:
            raise ValueError(
                f"{kind} version {v!r} not served (served: {served})")
    out = copy.deepcopy(data)
    if from_version != STORAGE_VERSION:
        out = _TO_HUB[(kind, from_version)](out)
    if to_version != STORAGE_VERSION:
        out = _FROM_HUB[(kind, to_version)](out)
    out["apiVersion"] = f"{GROUP}/{to_version}"
    return out


def resource_from_versioned_dict(data: dict[str, Any]) -> core.Resource:
    """Any served version -> typed (storage-version) resource."""
    return core.resource_from_dict(convert_dict(data, STORAGE_VERSION))


def to_versioned_dict(obj: core.Resource, version: str) -> dict[str, Any]:
    """Typed resource -> serialized form at the requested version."""
    return convert_dict(obj.to_dict(), version)


# ---------------------------------------------------------------------------
# Notebook conversions (ref notebook_conversion.go — ours carry real
# schema changes, not stubs)
# ---------------------------------------------------------------------------


def _stash(spec_tpu: dict, meta: dict) -> None:
    """Record hub-only tpu fields in annotations before dropping them."""
    ann = meta.setdefault("annotations", {})
    num_slices = spec_tpu.get("num_slices", 1)
    if num_slices not in (1, "1", None):
        ann[NUM_SLICES_ANNOTATION] = str(num_slices)
    if spec_tpu.get("reserved"):
        ann[RESERVED_ANNOTATION] = "true"


def _unstash(spec_tpu: dict, meta: dict) -> None:
    ann = meta.get("annotations", {})
    if NUM_SLICES_ANNOTATION in ann:
        spec_tpu["num_slices"] = int(ann.pop(NUM_SLICES_ANNOTATION))
    if ann.pop(RESERVED_ANNOTATION, "") == "true":
        spec_tpu["reserved"] = True


def _nb_v1alpha1_to_hub(data: dict) -> dict:
    spec = data.get("spec", {})
    tpu = {
        "topology": spec.pop("accelerator", "") or "",
        "mesh": spec.pop("mesh", "") or "",
    }
    _unstash(tpu, data.get("metadata", {}))
    spec["tpu"] = tpu
    return data


def _nb_hub_to_v1alpha1(data: dict) -> dict:
    spec = data.get("spec", {})
    tpu = spec.pop("tpu", {}) or {}
    _stash(tpu, data.setdefault("metadata", {}))
    spec["accelerator"] = tpu.get("topology", "")
    spec["mesh"] = tpu.get("mesh", "")
    return data


def _nb_v1beta1_to_hub(data: dict) -> dict:
    spec = data.get("spec", {})
    tpu = spec.get("tpu", {}) or {}
    _unstash(tpu, data.get("metadata", {}))
    spec["tpu"] = tpu
    return data


def _nb_hub_to_v1beta1(data: dict) -> dict:
    spec = data.get("spec", {})
    tpu = dict(spec.get("tpu", {}) or {})
    _stash(tpu, data.setdefault("metadata", {}))
    tpu.pop("num_slices", None)
    tpu.pop("reserved", None)
    spec["tpu"] = tpu
    return data


register_conversion("Notebook", "v1alpha1",
                    to_hub=_nb_v1alpha1_to_hub,
                    from_hub=_nb_hub_to_v1alpha1)
register_conversion("Notebook", "v1beta1",
                    to_hub=_nb_v1beta1_to_hub,
                    from_hub=_nb_hub_to_v1beta1)


# ---------------------------------------------------------------------------
# Profile conversions (ref profile_types.go: served v1beta1 AND v1,
# storage v1 — api/v1/profile_types.go:59. The reference's two versions
# are structurally identical; ours carry the real schema delta between
# the k8s-shaped wire form and the TPU-first hub.)
# ---------------------------------------------------------------------------

# v1beta1 owner is an rbac Subject {kind, name, apiGroup} (ref
# ProfileSpec.Owner rbacv1.Subject); the hub keeps only the user id
# string, so a non-User subject kind rides an annotation to round-trip.
OWNER_KIND_ANNOTATION = f"{GROUP}/conversion.owner-kind"
# v1beta1 resourceQuotaSpec is the full k8s ResourceQuotaSpec; the hub
# keeps only the `hard` map, so the remaining fields (scopes,
# scopeSelector) ride an annotation — same round-trip rule as above.
QUOTA_EXTRAS_ANNOTATION = f"{GROUP}/conversion.quota-extras"
_RBAC_API_GROUP = "rbac.authorization.k8s.io"


def _pf_v1beta1_to_hub(data: dict) -> dict:
    spec = data.get("spec", {})
    owner = spec.get("owner", {}) or {}
    if isinstance(owner, dict):
        spec["owner"] = owner.get("name", "")
        kind = owner.get("kind", "") or "User"
        if kind != "User":
            data.setdefault("metadata", {}).setdefault(
                "annotations", {})[OWNER_KIND_ANNOTATION] = kind
    quota = spec.pop("resourceQuotaSpec", {}) or {}
    spec["resource_quota"] = dict(quota.get("hard", {}) or {})
    extras = {k: v for k, v in quota.items() if k != "hard"}
    if extras:
        import json as _json
        data.setdefault("metadata", {}).setdefault(
            "annotations", {})[QUOTA_EXTRAS_ANNOTATION] = (
            _json.dumps(extras, sort_keys=True))
    spec["plugins"] = [
        {"kind": p.get("kind", ""),
         "options": dict(p.get("spec", {}) or {})}
        for p in (spec.get("plugins") or [])
    ]
    status = data.get("status", {}) or {}
    conds = status.pop("conditions", None)
    if conds is not None:
        # Latest condition wins (status is controller-owned and
        # regenerated on reconcile; ref ProfileStatus.Conditions).
        last = conds[-1] if conds else {}
        status["phase"] = {"Successful": "Ready",
                           "Failed": "Failed"}.get(last.get("type", ""), "")
        status["message"] = last.get("message", "")
        data["status"] = status
    return data


def _pf_hub_to_v1beta1(data: dict) -> dict:
    spec = data.get("spec", {})
    ann = data.get("metadata", {}).get("annotations", {})
    spec["owner"] = {
        "kind": ann.pop(OWNER_KIND_ANNOTATION, "User"),
        "name": spec.get("owner", "") or "",
        "apiGroup": _RBAC_API_GROUP,
    }
    quota_wire: dict = {"hard": dict(spec.pop("resource_quota", {}) or {})}
    if QUOTA_EXTRAS_ANNOTATION in ann:
        import json as _json
        quota_wire.update(_json.loads(ann.pop(QUOTA_EXTRAS_ANNOTATION)))
    spec["resourceQuotaSpec"] = quota_wire
    spec["plugins"] = [
        {"kind": p.get("kind", ""),
         "spec": dict(p.get("options", {}) or {})}
        for p in (spec.get("plugins") or [])
    ]
    status = data.get("status", {}) or {}
    phase = status.pop("phase", "")
    message = status.pop("message", "")
    cond_type = {"Ready": "Successful", "Failed": "Failed"}.get(phase)
    status["conditions"] = (
        [{"type": cond_type, "status": "True", "message": message}]
        if cond_type else [])
    data["status"] = status
    return data


register_conversion("Profile", "v1beta1",
                    to_hub=_pf_v1beta1_to_hub,
                    from_hub=_pf_hub_to_v1beta1)
