"""HPO controllers: Experiment → Trials → Pods.

Katib-equivalent control loop, restated in this framework's reconcile
kernel (the reference only smoke-tests Katib from outside,
`/root/reference/testing/katib_studyjob_test.py`):

- ExperimentController keeps `parallel_trials` Trials in flight until
  `max_trials` are created, then aggregates the best result. Suggestion
  state is deterministic: the suggester is keyed by (uid, seed) and
  replayed from the count of existing trials, so controller restarts
  don't double-suggest.
- TrialController renders the trial pod (hyperparameters as
  KFTPU_HP_<NAME> env), lets the normal TpuPodDefault webhook inject TPU
  topology env (the BASELINE "HPO sweep w/ env injection" path), and
  mirrors the pod's reported metric annotation into Trial.status.

Hermetic execution: `TrialExecutor` is the fake-kubelet for trial pods —
it "runs" the objective in-process when registered (tests, local mode).
Production leaves it None; a metric-reporter sidecar writes the
annotation instead.
"""

from __future__ import annotations

import logging
from typing import Callable

from kubeflow_tpu.api.core import EnvVar, Pod
from kubeflow_tpu.api.crds import (
    EXPERIMENT_LABEL,
    Experiment,
    ParameterSpec,
    TRIAL_INTERMEDIATE_ANNOTATION,
    TRIAL_LABEL,
    TRIAL_METRIC_ANNOTATION,
    Trial,
)
from kubeflow_tpu.controlplane.runtime import Controller, Result
from kubeflow_tpu.controlplane.store import (
    AdmissionDenied,
    AlreadyExists,
    Conflict,
    NotFound,
    OwnerGone,
    Store,
    set_controller_reference,
)
from kubeflow_tpu.hpo import search as search_lib

log = logging.getLogger(__name__)

# In-process objective for hermetic trials: (assignment) -> metric.
TrialExecutor = Callable[[dict[str, str]], float]

# Stepwise hermetic objective: (assignment, step_index) -> intermediate
# value, or None when training is done (final metric = last
# intermediate). One step runs per reconcile and each step persists to
# the pod annotation BEFORE the next runs — durable like the one-shot
# executor's outcome, and it gives the Experiment controller real
# between-step windows to apply the median stopping rule in.
StepwiseTrialExecutor = Callable[[dict[str, str], int], float | None]


def _parse_intermediates(raw: str) -> list[list[float]] | None:
    """Validate a pod's intermediate-metrics annotation: JSON list of
    [step, value] numeric pairs, or None if malformed (annotations are
    client-writable; the controller must not crash on garbage)."""
    import json

    try:
        v = json.loads(raw)
    except ValueError:
        return None
    if not isinstance(v, list):
        return None
    out: list[list[float]] = []
    for e in v:
        if (not isinstance(e, (list, tuple)) or len(e) != 2
                or not all(isinstance(x, (int, float))
                           and not isinstance(x, bool) for x in e)):
            return None
        out.append([float(e[0]), float(e[1])])
    return out


def _space_from_spec(params: list[ParameterSpec]) -> search_lib.SearchSpace:
    out: list[search_lib.Parameter] = []
    for p in params:
        if p.type == "double":
            out.append(search_lib.Double(p.name, p.min, p.max, log=p.log))
        elif p.type == "int":
            out.append(search_lib.Integer(p.name, int(p.min), int(p.max)))
        elif p.type == "categorical":
            out.append(search_lib.Categorical(p.name, tuple(p.values)))
        else:
            raise ValueError(f"unknown parameter type {p.type!r}")
    return search_lib.SearchSpace(tuple(out))


class ExperimentController(Controller):
    KIND = "Experiment"
    OWNS = ("Trial",)

    @staticmethod
    def _best(goal: str, values) -> float | None:
        vals = list(values)
        if not vals:
            return None
        return min(vals) if goal == "minimize" else max(vals)

    def _apply_early_stopping(self, store: Store, spec, running,
                              done) -> int:
        """Median stopping rule (the Katib `medianstop` semantics,
        best-by-step variant): stop a running trial whose best
        objective by its latest reported step s is worse than the
        median of completed trials' best values by step s. Completed
        trials without intermediate reports are excluded — mixing
        final values measured at different budgets into the median
        would bias the rule. Returns the number of trials stopped."""
        es = spec.early_stopping
        if es.algorithm != "medianstop":
            return 0
        goal = spec.objective.goal
        stopped = 0
        for t in running:
            inter = t.status.intermediates
            if not inter:
                continue
            s = inter[-1][0]
            if s < es.start_step:
                continue
            mine = self._best(goal, (v for _, v in inter))
            # Peers = SUCCEEDED trials only (Katib semantics): letting
            # early-stopped bests into the pool would drag the median
            # toward the very trials the rule cut, progressively
            # disarming it.
            peers = []
            for d in done:
                if d.status.phase != "Succeeded":
                    continue
                by_s = [v for st, v in d.status.intermediates if st <= s]
                if by_s:
                    peers.append(self._best(goal, by_s))
            if len(peers) < es.min_trials:
                continue
            peers.sort()
            mid = len(peers) // 2
            median = (peers[mid] if len(peers) % 2
                      else (peers[mid - 1] + peers[mid]) / 2.0)
            worse = (mine > median if goal == "minimize"
                     else mine < median)
            if not worse:
                continue
            # Mutate a clone: a Conflict must leave the local object
            # (and the caller's running/done refilter) untouched, or
            # an unpersisted "stop" would shrink `running` and
            # overshoot parallel_trials with extra pods.
            won = t.clone()
            won.status.phase = "EarlyStopped"
            won.status.value = mine
            won.status.message = (
                f"median stopping rule: best {mine:.6g} by step "
                f"{int(s)} vs median {median:.6g} of {len(peers)} "
                f"completed trials")
            try:
                store.update(won)
            except (Conflict, NotFound):
                continue  # the trial moved under us; re-judged next time
            t.status = won.status
            stopped += 1
        return stopped

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        try:
            exp = store.get("Experiment", namespace, name)
        except NotFound:
            return Result()
        assert isinstance(exp, Experiment)
        spec = exp.spec

        trials = [
            t for t in store.list("Trial", namespace)
            if t.spec.experiment == name
        ]
        running = [t for t in trials if t.status.phase in ("", "Running")]
        done = [t for t in trials
                if t.status.phase in ("Succeeded", "Failed",
                                      "EarlyStopped")]

        # Early stopping (medianstop): free underperformers' compute.
        # An EarlyStopped trial is terminal — it counts toward
        # max_trials, keeps its best-so-far as a REAL (truncated)
        # observation for TPE and the best-trial aggregate, and its
        # pod is deleted by the TrialController.
        stopped_now = self._apply_early_stopping(store, spec, running,
                                                 done)
        if stopped_now:
            running = [t for t in running
                       if t.status.phase in ("", "Running")]
            done = [t for t in trials
                    if t.status.phase in ("Succeeded", "Failed",
                                          "EarlyStopped")]

        # Spawn up to the parallelism budget. The suggester is recreated
        # deterministically and fast-forwarded past prior suggestions.
        to_create = min(
            spec.parallel_trials - len(running),
            spec.max_trials - len(trials),
        )
        if to_create > 0:
            try:
                space = _space_from_spec(spec.parameters)
                seeded = spec.algorithm in search_lib.SEEDED_ALGORITHMS
                suggester = search_lib.make_suggester(
                    spec.algorithm, space,
                    **({"seed": spec.seed} if seeded else {}))
            except ValueError as e:
                if (exp.status.phase, exp.status.message) != (
                    "Failed", str(e)
                ):  # update-on-change only: see livelock note below
                    exp.status.phase = "Failed"
                    exp.status.message = str(e)
                    store.update(exp)
                return Result()
            if hasattr(suggester, "observe"):
                # Adaptive algorithms (TPE) learn from finished trials —
                # including early-stopped ones, whose best-so-far is a
                # real (truncated) measurement; unparseable assignments
                # (edited by hand) are skipped rather than failing the
                # experiment.
                obs = []
                for t in done:
                    if t.status.phase in ("Succeeded", "EarlyStopped") \
                            and t.status.value is not None:
                        try:
                            obs.append((space.parse(t.spec.assignment),
                                        t.status.value))
                        except ValueError:
                            pass
                suggester.observe(obs, spec.objective.goal)
            suggester.advance(len(trials))           # replay / advance
            batch = suggester.suggest(to_create)
            # Re-get immediately before creating: a DELETE landing after
            # the read at the top of this reconcile has already cascaded
            # the existing Trials, and creating more with the stale uid
            # would orphan them (store.OwnerGone backstops the remaining
            # get→create window).
            try:
                exp = store.get("Experiment", namespace, name)
            except NotFound:
                return Result()
            if exp.metadata.deletion_timestamp is not None:
                return Result()
            for a in batch:
                idx = len(trials)
                trial = Trial()
                trial.metadata.name = f"{name}-{idx}"
                trial.metadata.namespace = namespace
                trial.metadata.labels = {EXPERIMENT_LABEL: name}
                trial.spec.experiment = name
                trial.spec.assignment = {k: str(v) for k, v in a.items()}
                trial.spec.template = spec.trial_template
                trial.spec.tpu = spec.tpu
                trial.spec.objective_metric = spec.objective.metric
                set_controller_reference(exp, trial)
                try:
                    store.create(trial)
                    trials.append(trial)
                except AlreadyExists:
                    pass
                except OwnerGone:
                    # Deleted in the get→create window; the cascade
                    # already collected the children. Stop creating.
                    return Result()

        # Aggregate status. (Grid exhaustion below max_trials is closed
        # out by the `finished` condition: no running, all trials done.)
        succeeded = [t for t in done if t.status.phase == "Succeeded"]
        early = [t for t in done if t.status.phase == "EarlyStopped"]
        best = None
        for t in succeeded + early:  # truncated runs still measured
            if t.status.value is None:
                continue
            if best is None or search_lib.better(
                spec.objective.goal, t.status.value, best.status.value
            ):
                best = t
        import dataclasses as _dc
        old_status = _dc.asdict(exp.status)
        exp.status.trials_created = len(trials)
        exp.status.trials_succeeded = len(succeeded)
        exp.status.trials_early_stopped = len(early)
        exp.status.trials_failed = len(done) - len(succeeded) - len(early)
        if best is not None:
            exp.status.best_trial = best.metadata.name
            exp.status.best_value = best.status.value
            exp.status.best_assignment = dict(best.spec.assignment)
        finished = (len(done) >= spec.max_trials
                    or (not running and len(trials) == len(done)
                        and len(trials) > 0 and spec.algorithm == "grid"
                        and len(trials) < spec.max_trials))
        if finished:
            exp.status.phase = (
                "Succeeded" if succeeded or best is not None else "Failed")
        elif trials:
            exp.status.phase = "Running"
        # Update only on change: an unconditional write would emit
        # MODIFIED, re-enqueue this controller, and livelock.
        if _dc.asdict(exp.status) != old_status:
            store.update(exp)
        return Result()


class TrialController(Controller):
    KIND = "Trial"
    OWNS = ("Pod",)

    def __init__(self, executor: TrialExecutor | None = None,
                 stepwise_executor: StepwiseTrialExecutor | None = None):
        if executor is not None and stepwise_executor is not None:
            raise ValueError(
                "pass executor OR stepwise_executor, not both")
        self.executor = executor
        self.stepwise = stepwise_executor

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        try:
            trial = store.get("Trial", namespace, name)
        except NotFound:
            return Result()
        assert isinstance(trial, Trial)
        if trial.status.phase == "EarlyStopped":
            # terminal by the Experiment's median rule: free the
            # compute NOW — the pod (and any in-flight stepwise work)
            # is torn down instead of running to max steps
            pod = store.try_get("Pod", namespace, f"{name}-run")
            if pod is not None:
                try:
                    store.delete("Pod", namespace, pod.metadata.name)
                except NotFound:
                    pass
            return Result()
        if trial.status.phase in ("Succeeded", "Failed"):
            return Result()

        pod_name = f"{name}-run"
        pod = store.try_get("Pod", namespace, pod_name)
        if pod is None:
            pod = Pod(spec=trial.spec.template.spec).clone()
            pod.metadata.name = pod_name
            pod.metadata.namespace = namespace
            pod.metadata.labels = {
                **trial.spec.template.metadata.labels,
                TRIAL_LABEL: name,
                EXPERIMENT_LABEL: trial.spec.experiment,
            }
            pod.metadata.annotations = dict(
                trial.spec.template.metadata.annotations)
            # Hyperparameters as env for the training script; the pod
            # webhook additionally injects TPU topology env.
            for c in pod.spec.containers:
                c.env.append(EnvVar("KFTPU_TRIAL_NAME", name))
                for k, v in sorted(trial.spec.assignment.items()):
                    c.env.append(EnvVar(f"KFTPU_HP_{k.upper()}", v))
            set_controller_reference(trial, pod)
            try:
                store.create(pod)
            except AlreadyExists:
                pass
            except OwnerGone:
                return Result()  # trial deleted in the get→create window
            except AdmissionDenied as e:
                trial.status.phase = "Failed"
                trial.status.message = f"pod admission denied: {e}"
                store.update(trial)
                return Result()
            # Re-fetch: admission webhooks mutated the stored copy; writing
            # through the stale local one would Conflict and re-run the
            # executor on retry.
            pod = store.get("Pod", namespace, pod_name)
            trial.status.phase = "Running"
            trial = store.update(trial)  # keep rv fresh for the mirror below

        # Stepwise hermetic executor: ONE training step per reconcile,
        # each persisted to the pod's intermediate-metrics annotation
        # before the next runs. Between steps the Experiment controller
        # gets a real window to apply the median stopping rule — which
        # is the point: early stopping is unobservable if the whole run
        # completes inside one reconcile.
        if self.stepwise is not None and pod.phase not in (
            "Succeeded", "Failed"
        ):
            import json as _json

            # same guarded parse as the mirror path below: the
            # annotation is client-writable, and garbage must not wedge
            # the reconcile loop (at-least-once semantics let us
            # restart the step count from a clean slate)
            inter = _parse_intermediates(pod.metadata.annotations.get(
                TRIAL_INTERMEDIATE_ANNOTATION, "[]"))
            if inter is None:
                log.warning("trial %s: unparseable intermediate "
                            "metrics annotation; restarting reports",
                            name)
                inter = []
            try:
                v = self.stepwise(dict(trial.spec.assignment), len(inter))
            except Exception as e:  # noqa: BLE001 — user objective
                log.warning("trial %s step objective failed: %s", name, e)
                # keep `inter` as reported so far: the recorded history
                # survives the failure (on the pod AND the mirror below)
                v = None
                pod.phase = "Failed"
            if pod.phase != "Failed":
                if v is None:
                    if inter:
                        pod.phase = "Succeeded"
                        pod.metadata.annotations[TRIAL_METRIC_ANNOTATION] \
                            = str(inter[-1][1])
                    else:
                        pod.phase = "Failed"  # done before any report
                else:
                    inter.append([len(inter) + 1, float(v)])
                    pod.metadata.annotations[
                        TRIAL_INTERMEDIATE_ANNOTATION] = _json.dumps(inter)
            for _ in range(8):
                try:
                    pod = store.update(pod)
                    break
                except Conflict:
                    try:
                        fresh = store.get("Pod", namespace, pod_name)
                    except NotFound:
                        return Result()  # early-stopped/deleted mid-step
                    if fresh.phase in ("Succeeded", "Failed"):
                        pod = fresh
                        break
                    # re-apply this step's outcome onto the fresh copy
                    fresh.phase = pod.phase
                    fresh.metadata.annotations.update({
                        k: pod.metadata.annotations[k]
                        for k in (TRIAL_METRIC_ANNOTATION,
                                  TRIAL_INTERMEDIATE_ANNOTATION)
                        if k in pod.metadata.annotations})
                    pod = fresh
                except NotFound:
                    return Result()
            else:
                log.error("trial %s: could not record step", name)
                return Result(requeue_after=1.0)
            # Mirror progress so the Experiment controller can judge —
            # from the PERSISTED pod, not the local step: a Conflict
            # retry may have kept another writer's terminal pod, and
            # mirroring an unpersisted extra step would let Trial.status
            # disagree with the pod's durable record.
            inter = _parse_intermediates(pod.metadata.annotations.get(
                TRIAL_INTERMEDIATE_ANNOTATION, "[]")) or []
            if trial.status.intermediates != inter \
                    or trial.status.phase != "Running":
                trial.status.intermediates = inter
                trial.status.phase = trial.status.phase or "Running"
                try:
                    trial = store.update(trial)
                except (Conflict, NotFound):
                    return Result(requeue_after=0.001)  # re-judged next
            if pod.phase not in ("Succeeded", "Failed"):
                return Result(requeue_after=0.001)  # next step

        # Mirror the pod's intermediate reports into Trial.status in
        # EVERY mode — production pods' metric-reporter writes the
        # annotation directly and the Experiment's median rule reads
        # Trials, not Pods. (The stepwise branch above mirrors eagerly;
        # this is a no-op there.) Malformed annotations are ignored
        # with a warning rather than wedging the reconcile loop.
        raw_inter = pod.metadata.annotations.get(
            TRIAL_INTERMEDIATE_ANNOTATION)
        if raw_inter is not None:
            parsed = _parse_intermediates(raw_inter)
            if parsed is None:
                log.warning("trial %s: unparseable intermediate "
                            "metrics annotation", name)
            elif parsed != trial.status.intermediates:
                trial.status.intermediates = parsed
                if pod.phase not in ("Succeeded", "Failed"):
                    trial.status.phase = trial.status.phase or "Running"
                    try:
                        trial = store.update(trial)
                    except (Conflict, NotFound):
                        return Result()  # re-mirrored on the next event
                # terminal: the completion mirror below persists it

        # Hermetic executor: run the objective now and complete the pod.
        # The outcome's ONLY record is the pod itself (terminal phase +
        # metric annotation) — durable across controller restarts, unlike
        # the process-local memo this replaces. The objective therefore
        # must not finish a reconcile un-persisted: the write below
        # retries Conflicts in place with a refetch (k8s
        # retry.RetryOnConflict discipline, ref notebook_route.go:119-131)
        # instead of bailing to a later reconcile that would re-run it.
        if self.executor is not None and pod.phase not in (
            "Succeeded", "Failed"
        ):
            try:
                value = float(self.executor(dict(trial.spec.assignment)))
                outcome = ("Succeeded", str(value))
            except Exception as e:  # noqa: BLE001 — user objective
                outcome = ("Failed", None)
                log.warning("trial %s objective failed: %s", name, e)
            for _ in range(8):
                pod.phase, metric = outcome
                if metric is None:
                    pod.metadata.annotations.pop(
                        TRIAL_METRIC_ANNOTATION, None)
                else:
                    pod.metadata.annotations[TRIAL_METRIC_ANNOTATION] = metric
                try:
                    pod = store.update(pod)
                    break
                except Conflict:
                    try:
                        pod = store.get("Pod", namespace, pod_name)
                    except NotFound:
                        return Result()  # trial/pod deleted mid-run
                    if pod.phase in ("Succeeded", "Failed"):
                        break  # another writer finished it; keep theirs
                except NotFound:
                    return Result()  # deleted while the objective ran
            else:
                # Pathological write contention: requeue; the objective
                # re-runs, which at-least-once semantics permit.
                log.error("trial %s: could not record outcome", name)
                return Result(requeue_after=1.0)

        # Mirror pod completion into trial status.
        if pod.phase == "Succeeded":
            raw = pod.metadata.annotations.get(TRIAL_METRIC_ANNOTATION)
            if raw is None:
                trial.status.phase = "Failed"
                trial.status.message = (
                    "pod succeeded without reporting "
                    f"{TRIAL_METRIC_ANNOTATION}")
            else:
                try:
                    trial.status.value = float(raw)
                    trial.status.phase = "Succeeded"
                except ValueError:
                    trial.status.phase = "Failed"
                    trial.status.message = f"unparseable metric {raw!r}"
            store.update(trial)
        elif pod.phase == "Failed":
            trial.status.phase = "Failed"
            trial.status.message = "trial pod failed"
            store.update(trial)
        return Result()
