"""Workload layer: StatefulSet → Pods, gang-aware TPU scheduler.

The reference leans on kubelet/kube-scheduler (L0) to turn StatefulSets
into running pods; hermetic operation needs an in-process equivalent —
the same move envtest makes (real apiserver, no kubelet), except our
tests DO need pods to materialize (the TPU env webhook fires on pod
create). Two pieces:

- StatefulSetController: creates/deletes pods `<name>-<i>` to match
  spec.replicas, labels each with its gang ordinal, mirrors readiness.
  Gang atomicity (SURVEY.md §7 hard part a): for gang STS, capacity for
  the WHOLE slice is reserved before any pod is created — partial slices
  never start, they fail as a unit with a Warning event that the spawner
  UI surfaces (ref status.py:79-95 mines warning events for "why is my
  pod pending").
- Scheduler/NodePool: models TPU slice capacity per topology
  (`NodePool({"v5e-16": 2})` = two v5e-16 slices). Pods with a TPU
  node selector consume a slice host; others always fit.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from kubeflow_tpu.api.core import Pod, StatefulSet
from kubeflow_tpu.controlplane.runtime import Controller, Result
from kubeflow_tpu.controlplane.store import (
    AdmissionDenied,
    AlreadyExists,
    NotFound,
    Store,
    set_controller_reference,
)
from kubeflow_tpu.controlplane import webhook as wh
from kubeflow_tpu.parallel.mesh import SLICE_TOPOLOGIES


@dataclass
class NodePool:
    """TPU capacity by topology name → number of whole slices."""

    slices: dict[str, int] = field(default_factory=dict)
    cpu_unlimited: bool = True


class Scheduler:
    """Tracks slice allocations by gang. Thread-safe.

    Reservations are counted in WHOLE SLICES, not hosts: a slice is an
    ICI domain — a gang that spans part of one is meaningless, and a
    multi-slice job (Notebook.spec.tpu.num_slices > 1) must get all its
    slices or none (same all-or-nothing rule as within a slice, one
    level up). `hosts` is what the StatefulSet wants; the slice count is
    derived from the topology's hosts-per-slice.
    """

    def __init__(self, pool: NodePool):
        self.pool = pool
        self._lock = threading.Lock()
        # gang key -> (topology, hosts, whole slices reserved)
        self._reservations: dict[tuple[str, str], tuple[str, int, int]] = {}

    def try_reserve_gang(
        self, namespace: str, gang: str, topo_name: str, hosts: int
    ) -> bool:
        topo = SLICE_TOPOLOGIES.get(topo_name)
        if topo is None:
            return False
        need_slices = -(-hosts // topo.hosts)  # ceil: whole slices only
        with self._lock:
            key = (namespace, gang)
            prev = self._reservations.get(key)
            if prev is not None and prev == (topo_name, hosts, need_slices):
                return True
            # New reservation OR a resize (e.g. the Notebook's num_slices
            # was edited): re-admit against the pool with this gang's old
            # reservation excluded — a grown gang that no longer fits
            # must fail scheduling, not silently run under-reserved.
            used = sum(
                s for k, (t, _, s) in self._reservations.items()
                if t == topo_name and k != key
            )
            if used + need_slices > self.pool.slices.get(topo_name, 0):
                return False
            self._reservations[key] = (topo_name, hosts, need_slices)
            return True

    def release_gang(self, namespace: str, gang: str) -> None:
        with self._lock:
            self._reservations.pop((namespace, gang), None)

    def reserved(self, namespace: str, gang: str) -> bool:
        with self._lock:
            return (namespace, gang) in self._reservations

    def reserved_slices(self, namespace: str, gang: str) -> int:
        with self._lock:
            res = self._reservations.get((namespace, gang))
            return res[2] if res else 0


class StatefulSetController(Controller):
    KIND = "StatefulSet"
    OWNS = ("Pod",)

    def __init__(self, scheduler: Scheduler | None = None):
        self.scheduler = scheduler or Scheduler(NodePool())

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        try:
            sts = store.get("StatefulSet", namespace, name)
        except NotFound:
            self.scheduler.release_gang(namespace, name)
            return Result()
        assert isinstance(sts, StatefulSet)

        want = sts.spec.replicas
        tmpl = sts.spec.template
        topo_name = tmpl.metadata.labels.get(wh.TOPOLOGY_LABEL, "")

        # Gang admission: reserve the whole slice first (all-or-nothing).
        if want > 0 and topo_name:
            if not self.scheduler.try_reserve_gang(
                namespace, name, topo_name, want
            ):
                existing = {
                    (e.reason) for e in store.events_for(
                        "StatefulSet", namespace, name)
                }
                if "FailedScheduling" not in existing:
                    topo = SLICE_TOPOLOGIES.get(topo_name)
                    n_slices = -(-want // topo.hosts) if topo else 1
                    store.emit_event(
                        sts, "Warning", "FailedScheduling",
                        f"insufficient TPU capacity for {topo_name} "
                        f"({n_slices} whole slice(s) = {want} hosts "
                        "required, gang is all-or-nothing)",
                    )
                return Result(requeue_after=0.5)
        if want == 0 and topo_name:
            self.scheduler.release_gang(namespace, name)

        pods = {
            p.metadata.name: p
            for p in store.list("Pod", namespace,
                                owner_uid=sts.metadata.uid)
        }

        # Template drift replaces pods: a resized/edited gang (e.g.
        # num_slices bumped) changes the injected env of EVERY member —
        # keeping old pods would leave a permanently split gang (half
        # the workers with the old KFTPU_NUM_PROCESSES, jax.distributed
        # waiting forever). Stale pods are deleted here and recreated
        # with the current template on the same pass.
        tmpl_hash = _template_hash(tmpl)
        stale = [
            p for p in pods.values()
            if p.metadata.annotations.get(TEMPLATE_HASH_ANNOTATION)
            != tmpl_hash
        ]
        for pod in stale:
            try:
                store.delete("Pod", namespace, pod.metadata.name)
            except NotFound:
                pass
            pods.pop(pod.metadata.name, None)

        # Slice-health recovery (SURVEY §5): a TPU gang is ONE SPMD
        # program — a single failed worker leaves every peer hung in a
        # collective, so the gang fails AND RESTARTS as a unit. Delete
        # every member; this same pass recreates them, the webhook
        # re-injects worker env, and the kernel bootstrap re-forms the
        # jax.distributed process group (coordinator restart = pod-0
        # recreated). Exponential backoff via STS annotations bounds
        # crash-looping workloads.
        failed = [p for p in pods.values() if p.phase == "Failed"]
        if want > 0 and failed:
            import time as _time

            ann = sts.metadata.annotations
            count = int(ann.get(GANG_RESTART_COUNT_ANNOTATION, "0"))
            last = float(ann.get(GANG_RESTART_TS_ANNOTATION, "0"))
            backoff = min(2.0 ** count, 60.0)
            now = _time.time()
            if now - last < backoff:
                return Result(requeue_after=backoff - (now - last))
            # Record the restart BEFORE destroying anything: a Conflict
            # here aborts cleanly (runtime retries with the gang
            # intact); the reverse order would delete the gang and lose
            # the count + event on the retry pass.
            ann[GANG_RESTART_COUNT_ANNOTATION] = str(count + 1)
            ann[GANG_RESTART_TS_ANNOTATION] = str(now)
            sts = store.update(sts)  # Conflict -> runtime retries us
            store.emit_event(
                sts, "Warning", "GangRestart",
                f"worker {failed[0].metadata.name} failed; restarting "
                f"the whole gang (restart #{count + 1}) — a TPU gang "
                "is one SPMD program and must re-rendezvous together")
            for pod in list(pods.values()):
                try:
                    store.delete("Pod", namespace, pod.metadata.name)
                except NotFound:
                    pass
                pods.pop(pod.metadata.name, None)

        for i in range(want):
            pod_name = f"{name}-{i}"
            if pod_name in pods:
                continue
            pod = Pod(spec=tmpl.spec)
            pod = pod.clone()
            pod.metadata.name = pod_name
            pod.metadata.namespace = namespace
            pod.metadata.labels = {
                **tmpl.metadata.labels,
                wh.GANG_ORDINAL_LABEL: str(i),
            }
            pod.metadata.annotations = {
                **tmpl.metadata.annotations,
                TEMPLATE_HASH_ANNOTATION: tmpl_hash,
            }
            pod.spec.hostname = pod_name
            pod.spec.subdomain = sts.spec.service_name
            set_controller_reference(sts, pod)
            try:
                store.create(pod)
            except AlreadyExists:
                pass
            except AdmissionDenied as e:
                store.emit_event(sts, "Warning", "AdmissionDenied", str(e))
                # Don't hold the slice hostage while no pod can start;
                # requeue so removing the conflicting TpuPodDefault
                # eventually recovers (those changes don't enqueue us).
                self.scheduler.release_gang(namespace, name)
                return Result(requeue_after=2.0)

        for pod_name, pod in pods.items():
            try:
                ordinal = int(pod_name.rsplit("-", 1)[1])
            except (IndexError, ValueError):
                ordinal = 0
            if ordinal >= want:
                try:
                    store.delete("Pod", namespace, pod_name)
                except NotFound:
                    pass

        # Simulated kubelet: freshly created pods become Running+ready.
        for p in store.list("Pod", namespace, owner_uid=sts.metadata.uid):
            if p.phase == "Pending":
                p.phase = "Running"
                p.ready = True
                p.pod_ip = f"10.0.{abs(hash((namespace, p.metadata.name))) % 250}.{abs(hash(p.metadata.name)) % 250}"
                p.host_ip = f"node-{abs(hash(p.metadata.name)) % 8}"
                store.update(p)

        ready = sum(
            1 for p in store.list("Pod", namespace,
                                  owner_uid=sts.metadata.uid)
            if p.phase == "Running" and p.ready
        )
        fresh = store.try_get("StatefulSet", namespace, name)
        if fresh is not None:
            changed = fresh.ready_replicas != ready
            fresh.ready_replicas = ready
            f_ann = fresh.metadata.annotations
            if (ready == want and want > 0
                    and GANG_RESTART_COUNT_ANNOTATION in f_ann):
                # Fully healthy again: a LATER failure deserves a fresh
                # (fast) restart, not the accumulated backoff — but only
                # after the gang has STAYED healthy for the current
                # backoff window. Clearing on the same pass that
                # restarted would reset the counter every cycle and the
                # exponential backoff would never engage on a
                # crash-looping workload.
                import time as _time

                r_count = int(f_ann.get(GANG_RESTART_COUNT_ANNOTATION,
                                        "0"))
                r_last = float(f_ann.get(GANG_RESTART_TS_ANNOTATION,
                                         "0"))
                stability = min(2.0 ** r_count, 60.0)
                if _time.time() - r_last >= stability:
                    f_ann.pop(GANG_RESTART_COUNT_ANNOTATION, None)
                    f_ann.pop(GANG_RESTART_TS_ANNOTATION, None)
                    changed = True
                else:
                    # come back to clear once the window has passed
                    if changed:
                        store.update(fresh)
                    return Result(requeue_after=stability
                                  - (_time.time() - r_last))
            if changed:
                store.update(fresh)
        return Result()


class DeploymentController(Controller):
    """Deployment → pods (unordered, no gang). Serves the tensorboard
    controller's Deployments the way the STS controller serves notebooks."""

    KIND = "Deployment"
    OWNS = ("Pod",)

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        from kubeflow_tpu.api.core import Deployment

        try:
            dep = store.get("Deployment", namespace, name)
        except NotFound:
            return Result()
        assert isinstance(dep, Deployment)
        want = dep.spec.replicas
        tmpl = dep.spec.template
        tmpl_hash = _template_hash(tmpl)

        owned = store.list("Pod", namespace,
                           owner_uid=dep.metadata.uid)
        # Rolling replacement: pods from an older template are retired so
        # a spec change (e.g. a Tensorboard's new --logdir) actually
        # lands; FAILED pods retire the same way (restartPolicy-Always
        # semantics — no gang coupling here, each pod restarts alone).
        stale = [
            p for p in owned
            if p.metadata.annotations.get(TEMPLATE_HASH_ANNOTATION)
            != tmpl_hash or p.phase == "Failed"
        ]
        for pod in stale:
            try:
                store.delete("Pod", namespace, pod.metadata.name)
            except NotFound:
                pass
        owned = [p for p in owned if p not in stale]

        for i in range(want - len(owned)):
            pod = Pod(spec=tmpl.spec).clone()
            pod.metadata.name = f"{name}-{uuid_suffix()}"
            pod.metadata.namespace = namespace
            pod.metadata.labels = dict(tmpl.metadata.labels)
            pod.metadata.annotations = {
                **tmpl.metadata.annotations,
                TEMPLATE_HASH_ANNOTATION: tmpl_hash,
            }
            set_controller_reference(dep, pod)
            try:
                store.create(pod)
            except AdmissionDenied as e:
                store.emit_event(dep, "Warning", "AdmissionDenied", str(e))
                return Result(requeue_after=2.0)
            except AlreadyExists:
                pass
        for pod in owned[want:]:
            try:
                store.delete("Pod", namespace, pod.metadata.name)
            except NotFound:
                pass

        ready = 0
        for p in store.list("Pod", namespace,
                            owner_uid=dep.metadata.uid):
            if p.phase == "Pending":
                p.phase = "Running"
                p.ready = True
                p.host_ip = f"node-{abs(hash(p.metadata.name)) % 8}"
                store.update(p)
            if p.phase == "Running":
                ready += 1
        fresh = store.try_get("Deployment", namespace, name)
        if fresh is not None and fresh.ready_replicas != ready:
            fresh.ready_replicas = ready
            fresh.conditions = [{"type": "Available",
                                 "status": str(ready >= want)}]
            store.update(fresh)
        return Result()


TEMPLATE_HASH_ANNOTATION = "kubeflow-tpu.dev/template-hash"
GANG_RESTART_COUNT_ANNOTATION = "kubeflow-tpu.dev/gang-restart-count"
GANG_RESTART_TS_ANNOTATION = "kubeflow-tpu.dev/gang-restart-ts"


def _template_hash(tmpl) -> str:
    import dataclasses
    import hashlib
    import json

    blob = json.dumps(dataclasses.asdict(tmpl), sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def uuid_suffix() -> str:
    import uuid as _uuid

    return _uuid.uuid4().hex[:6]
