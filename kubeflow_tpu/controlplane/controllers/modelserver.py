"""ModelServer controller: ModelServer CR → Deployment + Service + route.

Closes the serving loop the reference only documents: its TF-Serving
component (removed; `/root/reference/docs_dev/tf_serving.md:1-60`,
smoke-tested by `/root/reference/testing/test_tf_serving.py`) was a
Deployment behind the same Service/VirtualService machinery as
notebooks. TPU-native restatement:

- the pod runs `python -m kubeflow_tpu.serving` (the engine CLI) with
  flags rendered from the spec — continuous batching + AOT warmup on
  by default, so Ready means "compiled, no first-request stall";
- checkpoint source dispatch mirrors the tensorboard controller's
  logspath dispatch (`tensorboard_controller.go:170-239` pattern):
  `pvc://name/subpath` mounts the PVC at /ckpt, `gs://` mounts the
  user-gcp-sa secret, "" runs --random (smoke/dev);
- TPU placement rides the SAME machinery as notebooks: topology label
  for the webhook's env injection, slice-pool node selector, chip
  resources (`controllers/notebook.py` wiring);
- route prefix `/serving/<ns>/<name>/` → the pod's REST port, and
  status.url surfaces it (`notebook_controller.go:483-510` pattern).
"""

from __future__ import annotations

import os
import time

from kubeflow_tpu.api.core import (
    Container,
    Deployment,
    DeploymentSpec,
    EnvVar,
    HTTPRoute,
    PodTemplateSpec,
    Probe,
    Service,
    ServicePort,
    ServiceSpec,
    VirtualService,
    VirtualServiceSpec,
    Volume,
    VolumeMount,
)
from kubeflow_tpu.api.crds import ModelServer
from kubeflow_tpu.controlplane.controllers.helpers import (
    copy_spec_and_labels,
    reconcile_child,
)
from kubeflow_tpu.controlplane.controllers.notebook import (
    TOPOLOGY_NODE_SELECTOR,
    TPU_RESOURCE_KEY,
)
from kubeflow_tpu.controlplane.runtime import Controller, Result
from kubeflow_tpu.controlplane.store import NotFound, Store
from kubeflow_tpu.controlplane import webhook as wh
from kubeflow_tpu.parallel.mesh import SLICE_TOPOLOGIES

# Mirror of serving.__main__.MODEL_NAMES: importing the serving package
# would pull jax into the control plane (which is deliberately jax-free
# — controllers must never touch a TPU backend). Drift is pinned by
# tests/test_modelserver.py.
MODEL_NAMES = ("llama-tiny", "llama3-1b", "llama3-8b", "gemma-tiny",
               "gemma-2b", "mixtral-tiny")

DEFAULT_IMAGE = "kubeflow-tpu/serving:latest"  # KFTPU_SERVING_IMAGE env
SERVE_PORT = 8000
MS_NAME_LABEL = "modelserver-name"
# Disaggregated pools render one Deployment per pool; the pool label
# keeps their selectors disjoint (two Deployments selecting the same
# label set would adopt each other's pods in a real cluster).
MS_POOL_LABEL = "modelserver-pool"

# Autoscale handshake (ISSUE 3): whatever consumes the fleet router's
# /fleet/autoscale recommendation writes the number here; the
# controller clamps it into [spec.replicas, spec.max_replicas].
DESIRED_REPLICAS_ANNOTATION = "kubeflow-tpu.dev/desired-replicas"
# Disaggregated twin (ISSUE 12): the consumer of
# /fleet/autoscale?pools=1 writes the per-pool split here; each is
# clamped into [spec.<pool>_replicas, spec.max_replicas].
DESIRED_PREFILL_ANNOTATION = "kubeflow-tpu.dev/desired-prefill-replicas"
DESIRED_DECODE_ANNOTATION = "kubeflow-tpu.dev/desired-decode-replicas"
# Scale-down protocol: excess pods are annotated draining-since first
# (a real deployment POSTs /fleet/drain, which now pushes every
# in-flight sequence to healthy peers via live KV-block migration);
# only after DRAIN_GRACE_S does the controller delete them and shrink
# the Deployment. With migrate-and-exit the replica is empty within
# ~2 s regardless of generation length, so the grace window matches
# that bound instead of the old wait-out-the-longest-generation guess.
# Module constant so tests shrink the window instead of sleeping.
DRAIN_ANNOTATION = "kubeflow-tpu.dev/draining-since"
DRAIN_GRACE_S = 2.0
# Rollout handshake (ISSUE 18): whatever consumes the fleet router's
# /fleet/versions registry (the promoted `current` version) writes it
# here; the rendered pods boot with `--model-version <value>` so a
# restarted replica re-registers under the promoted label instead of
# the stale spec default. Annotation wins over spec.model_version.
MODEL_VERSION_ANNOTATION = "kubeflow-tpu.dev/model-version"


class ModelServerController(Controller):
    KIND = "ModelServer"
    OWNS = ("Deployment", "Service", "VirtualService")

    def __init__(self, *, use_routing: bool = True):
        self.use_routing = use_routing

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        try:
            ms = store.get("ModelServer", namespace, name)
        except NotFound:
            return Result()
        assert isinstance(ms, ModelServer)

        # user-config errors surface as events, not retry loops (the
        # notebook controller's InvalidTopology discipline)
        problem = self._validate(ms)
        if problem:
            reason, msg = problem
            if not any(e.reason == reason for e in
                       store.events_for("ModelServer", namespace, name)):
                store.emit_event(ms, "Warning", reason, msg)
            return Result()

        disagg = ms.spec.prefill_replicas > 0
        requeue = None
        if disagg:
            # one Deployment per pool; each scale-down drains through
            # the same window as the symmetric path
            for suffix, pool, want in (
                    ("-prefill", "prefill",
                     self._desired_pool_count(store, ms, "prefill")),
                    ("-decode", "decode",
                     self._desired_pool_count(store, ms, "decode"))):
                child = name + suffix
                cur_dep = store.try_get("Deployment", namespace, child)
                if cur_dep is not None and want < cur_dep.spec.replicas:
                    want, rq = self._drain_scale_down(
                        store, ms, cur_dep, want)
                    if rq is not None:
                        requeue = rq if requeue is None \
                            else min(requeue, rq)
                dep = self._desired_deployment(
                    ms, replicas=want, pool=pool, child_name=child)
                reconcile_child(store, ms, dep, copy_spec_and_labels)
            # a spec flipped from symmetric: retire the old fleet
            try:
                store.delete("Deployment", namespace, name)
            except NotFound:
                pass
        else:
            desired = self._desired_replica_count(store, ms)
            cur_dep = store.try_get("Deployment", namespace, name)
            if cur_dep is not None and desired < cur_dep.spec.replicas:
                # scale-down drains before delete: hold the Deployment
                # at its current size while excess pods sit in their
                # drain window, then delete them and shrink
                desired, requeue = self._drain_scale_down(
                    store, ms, cur_dep, desired)
            dep = self._desired_deployment(ms, replicas=desired)
            reconcile_child(store, ms, dep, copy_spec_and_labels)
            for suffix in ("-prefill", "-decode"):
                # a spec flipped from disaggregated: retire the pools
                try:
                    store.delete("Deployment", namespace, name + suffix)
                except NotFound:
                    pass
        svc = self._desired_service(ms)
        reconcile_child(store, ms, svc, copy_spec_and_labels)
        if self.use_routing:
            vs = self._desired_virtualservice(ms)
            reconcile_child(store, ms, vs, copy_spec_and_labels)

        if disagg:
            deps = [store.try_get("Deployment", namespace,
                                  name + suffix)
                    for suffix in ("-prefill", "-decode")]
            ready = all(d is not None and d.ready_replicas >= 1
                        for d in deps)
            conditions = [c for d in deps if d
                          for c in d.conditions]
        else:
            cur = store.try_get("Deployment", namespace, name)
            ready = bool(cur and cur.ready_replicas >= 1)
            conditions = list(cur.conditions) if cur else []
        url = f"/serving/{namespace}/{name}/" if self.use_routing else \
            f"http://{name}.{namespace}.svc"
        fresh = store.try_get("ModelServer", namespace, name)
        if fresh is not None and (
                fresh.status.ready != ready
                or fresh.status.conditions != conditions
                or fresh.status.url != url):
            fresh.status.ready = ready
            fresh.status.conditions = conditions
            fresh.status.url = url
            store.update(fresh)
        return Result(requeue_after=requeue)

    def _desired_replica_count(self, store: Store, ms: ModelServer) -> int:
        """spec.replicas, lifted by the autoscale annotation when
        max_replicas enables it — clamped to [replicas, max_replicas]
        so a runaway recommender can never scale past the operator's
        ceiling or below the configured baseline."""
        spec = ms.spec
        desired = max(1, spec.replicas)
        ann = ms.metadata.annotations.get(DESIRED_REPLICAS_ANNOTATION)
        if ann is None or not spec.max_replicas:
            return desired
        try:
            want = int(ann)
        except ValueError:
            reason = "InvalidDesiredReplicas"
            if not any(e.reason == reason for e in store.events_for(
                    "ModelServer", ms.metadata.namespace,
                    ms.metadata.name)):
                store.emit_event(
                    ms, "Warning", reason,
                    f"annotation {DESIRED_REPLICAS_ANNOTATION}={ann!r} "
                    "is not an integer; using spec.replicas")
            return desired
        return max(spec.replicas, min(want, spec.max_replicas))

    def _desired_pool_count(self, store: Store, ms: ModelServer,
                            pool: str) -> int:
        """Per-pool twin of `_desired_replica_count`: the spec's pool
        size, lifted by the pool's autoscale annotation (written off
        `/fleet/autoscale?pools=1`) and clamped into
        [spec.<pool>_replicas, spec.max_replicas]."""
        spec = ms.spec
        floor = max(1, spec.prefill_replicas if pool == "prefill"
                    else spec.decode_replicas)
        ann_key = (DESIRED_PREFILL_ANNOTATION if pool == "prefill"
                   else DESIRED_DECODE_ANNOTATION)
        ann = ms.metadata.annotations.get(ann_key)
        if ann is None or not spec.max_replicas:
            return floor
        try:
            want = int(ann)
        except ValueError:
            reason = "InvalidDesiredReplicas"
            if not any(e.reason == reason for e in store.events_for(
                    "ModelServer", ms.metadata.namespace,
                    ms.metadata.name)):
                store.emit_event(
                    ms, "Warning", reason,
                    f"annotation {ann_key}={ann!r} is not an "
                    f"integer; using spec {pool} size")
            return floor
        return max(floor, min(want, spec.max_replicas))

    @staticmethod
    def _drain_scale_down(store: Store, ms: ModelServer, cur_dep,
                          desired: int):
        """Mark excess pods draining (newest first are removed; the
        oldest `desired` stay), hold the Deployment at its current
        size until every excess pod's drain window has elapsed, then
        delete the drained pods and let the Deployment shrink.
        Returns (replicas_to_render_now, requeue_after)."""
        ns, name = ms.metadata.namespace, ms.metadata.name
        now = time.time()
        pods = sorted(
            store.list("Pod", ns, owner_uid=cur_dep.metadata.uid),
            key=lambda p: (p.metadata.creation_timestamp,
                           p.metadata.name))
        excess = pods[desired:]
        if not excess:
            # pods already gone (or never created): shrink directly
            return desired, None
        remaining = 0.0
        newly = []
        for pod in excess:
            since = pod.metadata.annotations.get(DRAIN_ANNOTATION)
            if since is None:
                pod.metadata.annotations[DRAIN_ANNOTATION] = repr(now)
                store.update(pod)
                newly.append(pod.metadata.name)
                remaining = max(remaining, DRAIN_GRACE_S)
            else:
                remaining = max(
                    remaining, float(since) + DRAIN_GRACE_S - now)
        if newly:
            store.emit_event(
                ms, "Normal", "DrainingReplica",
                f"draining {len(newly)} replica pod(s) before "
                f"scale-down to {desired}")
        if remaining > 0:
            # hold at current size; requeue when the window closes
            return cur_dep.spec.replicas, remaining
        for pod in excess:
            try:
                store.delete("Pod", ns, pod.metadata.name)
            except NotFound:
                pass
        store.emit_event(ms, "Normal", "ScaledDown",
                         f"scaled {name} to {desired} replica(s) after "
                         "drain")
        return desired, None

    @staticmethod
    def _validate(ms: ModelServer):
        spec = ms.spec
        if spec.model not in MODEL_NAMES:
            return ("InvalidModel",
                    f"unknown model {spec.model!r}; known: "
                    f"{sorted(MODEL_NAMES)}")
        if spec.tpu.topology and spec.tpu.topology not in SLICE_TOPOLOGIES:
            return ("InvalidTopology",
                    f"unknown TPU slice topology {spec.tpu.topology!r}; "
                    f"known: {sorted(SLICE_TOPOLOGIES)}")
        if spec.quant not in ("", "int8"):
            return ("InvalidQuant",
                    f"unknown quant mode {spec.quant!r}")
        # non-positive numerics would render a Deployment whose CLI
        # dies at startup — a crash loop instead of this event
        if spec.max_len < 1 or spec.max_batch < 1 \
                or spec.prefill_chunk < 0:
            return ("InvalidSpec",
                    f"max_len ({spec.max_len}) and max_batch "
                    f"({spec.max_batch}) must be >= 1; prefill_chunk "
                    f"({spec.prefill_chunk}) must be >= 0")
        if spec.replicas < 1:
            return ("InvalidReplicas",
                    f"replicas ({spec.replicas}) must be >= 1")
        if spec.max_replicas and spec.max_replicas < spec.replicas:
            return ("InvalidReplicas",
                    f"max_replicas ({spec.max_replicas}) must be 0 "
                    f"(autoscale off) or >= replicas ({spec.replicas})")
        ckpt = spec.checkpoint
        if ckpt and not (ckpt.startswith("pvc://")
                         or ckpt.startswith("gs://")):
            return ("InvalidCheckpoint",
                    f"checkpoint {ckpt!r} must be pvc://name/path, "
                    "gs://bucket/path, or empty (random init)")
        if ckpt.startswith("pvc://") \
                and not ckpt[len("pvc://"):].partition("/")[0]:
            # an empty claim name would render an unbound volume whose
            # failure surfaces as an opaque kubelet error, not an event
            return ("InvalidCheckpoint",
                    f"checkpoint {ckpt!r} names no PVC")
        if ckpt.startswith("gs://") and not ckpt[len("gs://"):]:
            return ("InvalidCheckpoint",
                    f"checkpoint {ckpt!r} names no bucket")
        if spec.warmup and not spec.continuous:
            return ("InvalidWarmup",
                    "warmup requires continuous batching (the window "
                    "batcher has no ahead-of-traffic shape set)")
        if spec.prefill_replicas < 0 or spec.decode_replicas < 0:
            return ("InvalidReplicas",
                    f"prefill_replicas ({spec.prefill_replicas}) and "
                    f"decode_replicas ({spec.decode_replicas}) must "
                    "be >= 0")
        if (spec.prefill_replicas > 0) != (spec.decode_replicas > 0):
            return ("InvalidReplicas",
                    "disaggregation needs BOTH prefill_replicas and "
                    "decode_replicas > 0 (a lone pool cannot serve); "
                    "set both to 0 for a symmetric fleet")
        if spec.prefill_replicas > 0 and not spec.continuous:
            return ("InvalidPool",
                    "disaggregated pools require continuous batching "
                    "(the prefill->decode handoff ships paged KV "
                    "blocks)")
        return None

    def _desired_deployment(self, ms: ModelServer, replicas: int = 1,
                            pool: str = "",
                            child_name: str = "") -> Deployment:
        name, ns = ms.metadata.name, ms.metadata.namespace
        spec = ms.spec
        volumes: list[Volume] = []
        mounts: list[VolumeMount] = []
        env: list[EnvVar] = []

        args = ["--model", spec.model, "--port", str(SERVE_PORT),
                "--max-len", str(spec.max_len),
                "--max-batch", str(spec.max_batch)]
        ckpt = spec.checkpoint
        if ckpt.startswith("pvc://"):
            rest = ckpt[len("pvc://"):]
            pvc_name, _, sub_path = rest.partition("/")
            volumes.append(Volume(name="ckpt", pvc_name=pvc_name))
            mounts.append(VolumeMount(name="ckpt", mount_path="/ckpt",
                                      sub_path=sub_path))
            args += ["--checkpoint", "/ckpt"]
        elif ckpt.startswith("gs://"):
            volumes.append(Volume(name="gcp-creds", secret="user-gcp-sa"))
            mounts.append(VolumeMount(name="gcp-creds",
                                      mount_path="/secret/gcp"))
            env.append(EnvVar("GOOGLE_APPLICATION_CREDENTIALS",
                              "/secret/gcp/user-gcp-sa.json"))
            args += ["--checkpoint", ckpt]
        else:
            args += ["--random"]
        if spec.continuous:
            args += ["--continuous"]
        if spec.warmup:
            args += ["--warmup"]
        if spec.prefill_chunk:
            args += ["--prefill-chunk", str(spec.prefill_chunk)]
        if spec.quant:
            args += ["--quant", spec.quant]
        # "none"/"" force byte mode; "auto" lets the server pick up
        # tokenizer.json beside the checkpoint (the Checkpointer
        # carries it there from tools/prepare_data.py's output) so a
        # served prepared checkpoint speaks its training tokenizer.
        # ONLY "auto" is gated on a checkpoint being set (it is a
        # no-op without one, and not rendering it then keeps
        # random-init servers runnable on serving images predating the
        # auto mode); an EXPLICIT tokenizer path renders regardless —
        # silently dropping configuration the operator asked for would
        # serve byte-mode text with no error anywhere.
        if spec.tokenizer and spec.tokenizer != "none" \
                and (ckpt or spec.tokenizer != "auto"):
            args += ["--tokenizer", spec.tokenizer]
        if pool:
            args += ["--pool", pool]
        # model-version label (ISSUE 18): the annotation (written by
        # the rollout consumer after a promote) overrides the spec
        # default, so restarted pods re-register under the PROMOTED
        # version instead of resurrecting a stale label
        version = ms.metadata.annotations.get(
            MODEL_VERSION_ANNOTATION, "") or spec.model_version
        if version:
            args += ["--model-version", version]

        container = Container(
            name=child_name or name,
            image=os.environ.get("KFTPU_SERVING_IMAGE", DEFAULT_IMAGE),
            command=["python", "-m", "kubeflow_tpu.serving"],
            args=args,
            env=env,
            ports=[SERVE_PORT],
            volume_mounts=mounts,
            # Ready must mean LISTENING — checkpoint restore + warmup
            # compiles run for minutes before the port binds, and the
            # server only answers /readyz after on_startup (warmup)
            # finishes. Without this probe a real kubelet would mark
            # the pod Ready at process start and the route would serve
            # connection-refused.
            readiness_probe=Probe(path="/readyz", port=SERVE_PORT,
                                  initial_delay_seconds=5,
                                  period_seconds=5),
        )
        selector = {MS_NAME_LABEL: name}
        if pool:
            selector[MS_POOL_LABEL] = pool
        dep = Deployment(
            spec=DeploymentSpec(
                replicas=replicas,
                selector=dict(selector),
                template=PodTemplateSpec(),
            )
        )
        tmpl = dep.spec.template
        tmpl.metadata.labels = dict(selector)
        topo_name = spec.tpu.topology
        if topo_name:
            # same placement + webhook-env path as notebook gangs
            tmpl.metadata.labels[wh.TOPOLOGY_LABEL] = topo_name
            topo = SLICE_TOPOLOGIES[topo_name]
            tmpl.spec.node_selector.setdefault(
                TOPOLOGY_NODE_SELECTOR, topo_name)
            container.resources.limits.setdefault(
                TPU_RESOURCE_KEY, str(topo.chips_per_host))
        tmpl.spec.containers = [container]
        tmpl.spec.volumes = volumes
        dep.metadata.name = child_name or name
        dep.metadata.namespace = ns
        dep.metadata.labels = dict(selector)
        return dep

    def _desired_service(self, ms: ModelServer) -> Service:
        name, ns = ms.metadata.name, ms.metadata.namespace
        svc = Service(
            spec=ServiceSpec(
                selector={MS_NAME_LABEL: name},
                ports=[ServicePort("http", 80, SERVE_PORT)],
            )
        )
        svc.metadata.name = name
        svc.metadata.namespace = ns
        return svc

    def _desired_virtualservice(self, ms: ModelServer) -> VirtualService:
        name, ns = ms.metadata.name, ms.metadata.namespace
        vs = VirtualService(
            spec=VirtualServiceSpec(
                gateways=["kubeflow-gateway"],
                hosts=["*"],
                http=[HTTPRoute(
                    prefix=f"/serving/{ns}/{name}/",
                    rewrite="/",
                    destination_host=f"{name}.{ns}.svc",
                    destination_port=80,
                )],
            )
        )
        vs.metadata.name = f"modelserver-{ns}-{name}"
        vs.metadata.namespace = ns
        return vs
