"""Idle culling: kernel-activity probing → stop annotation.

Semantics from the reference's culler pkg (notebook-controller/pkg/
culler/culler.go), re-shaped for TPU economics (an idle v5e-16 slice
burns 16 chips, so culling is a first-class cost control):

- probe each running notebook's kernel/terminal activity over its
  in-cluster URL (ref getNotebookResourceResponse :155-180); here the
  transport is a pluggable `ActivityProbe` so tests inject activity
  hermetically (the reference's culler tests skip HTTP too, SURVEY.md §4);
- a notebook is active if ANY kernel is busy (ref allKernelsAreIdle
  :223-240); long-running training cells keep the kernel busy, so a
  3-day pretrain is never culled (SURVEY.md §7 hard part d);
- last activity tracked in an annotation (ref
  UpdateNotebookLastActivityAnnotation :266-300);
- idle > idle_time ⇒ SetStopAnnotation (ref :118-141), which the
  notebook controller turns into replicas=0. Restart = remove the
  annotation (spawner PATCH path).

Env knobs mirror the reference (culler.go:26-28): CULL_IDLE_TIME
(minutes, default 1440), IDLENESS_CHECK_PERIOD (minutes, default 1),
ENABLE_CULLING (default false).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Protocol

from kubeflow_tpu.api.crds import (
    CULLING_DISABLED_ANNOTATION,
    LAST_ACTIVITY_ANNOTATION,
    Notebook,
    STOP_ANNOTATION,
)
from kubeflow_tpu.controlplane.runtime import Controller, Result
from kubeflow_tpu.controlplane.store import Conflict, NotFound, Store

log = logging.getLogger(__name__)


@dataclass
class KernelStatus:
    execution_state: str = "idle"   # idle | busy
    last_activity: float = 0.0


class ActivityProbe(Protocol):
    """Transport for kernel/terminal activity. Production impl does HTTP
    GET http://<nb>.<ns>.svc/notebook/<ns>/<nb>/api/kernels (ref
    culler.go:155-180); tests inject a fake. `terminals` is optional
    (ref updateTimestampFromTerminalsActivity :357-382): probes without
    it cull on kernel activity alone."""

    def kernels(self, namespace: str, name: str) -> list[KernelStatus] | None:
        ...

    def terminals(self, namespace: str, name: str) -> list[float] | None:
        """last_activity timestamps of open terminals, None if
        unreachable/unsupported."""
        ...


class HTTPActivityProbe:
    """Probes the notebook pod's Jupyter REST API (ref culler.go:155-201).

    10s timeout per the reference (culler.go:19-21).

    DEV mode (ref culler.go:160-164: `DEV=true` proxies through a local
    `kubectl proxy` instead of in-cluster svc DNS): set
    `KFTPU_CULLER_DEV=true` to operate the culler OUT of cluster against
    a remote deployment — probes go through the apiserver service proxy
    at `KFTPU_DEV_PROXY_BASE` (default http://localhost:8001, kubectl
    proxy's default listen address).
    """

    def __init__(self, cluster_domain: str = "cluster.local",
                 timeout: float = 10.0, *, dev_mode: bool | None = None,
                 dev_proxy_base: str | None = None):
        import os

        self.cluster_domain = cluster_domain
        self.timeout = timeout
        self.dev_mode = (
            os.environ.get("KFTPU_CULLER_DEV", "").lower() == "true"
            if dev_mode is None else dev_mode)
        self.dev_proxy_base = (dev_proxy_base
                               or os.environ.get("KFTPU_DEV_PROXY_BASE",
                                                 "http://localhost:8001"))

    def url(self, namespace: str, name: str, resource: str) -> str:
        if self.dev_mode:
            # apiserver service-proxy path, same shape kubectl proxy
            # serves (ref culler.go:160-164 DEV branch).
            return (
                f"{self.dev_proxy_base}/api/v1/namespaces/{namespace}"
                f"/services/{name}/proxy/notebook/{namespace}/{name}"
                f"/api/{resource}"
            )
        return (
            f"http://{name}.{namespace}.svc.{self.cluster_domain}"
            f"/notebook/{namespace}/{name}/api/{resource}"
        )

    def _fetch(self, namespace: str, name: str, resource: str):
        import json
        import urllib.request

        url = self.url(namespace, name, resource)
        try:
            with urllib.request.urlopen(url, timeout=self.timeout) as r:
                return json.loads(r.read())
        except Exception:
            return None

    def kernels(self, namespace: str, name: str) -> list[KernelStatus] | None:
        data = self._fetch(namespace, name, "kernels")
        if data is None:
            return None
        out = []
        for k in data:
            ts = k.get("last_activity", 0)
            out.append(KernelStatus(k.get("execution_state", "idle"),
                                    _parse_ts(ts)))
        return out

    def terminals(self, namespace: str, name: str) -> list[float] | None:
        data = self._fetch(namespace, name, "terminals")
        if data is None:
            return None
        return [_parse_ts(t.get("last_activity", 0)) for t in data]


def _parse_ts(ts) -> float:
    if isinstance(ts, (int, float)):
        return float(ts)
    try:
        import datetime

        return datetime.datetime.fromisoformat(
            str(ts).replace("Z", "+00:00")
        ).timestamp()
    except ValueError:
        return 0.0


class Culler(Controller):
    """Runs as a controller over Notebooks with periodic requeue."""

    KIND = "Notebook"

    def __init__(
        self,
        probe: ActivityProbe,
        *,
        enabled: bool = True,
        idle_time: float = 1440 * 60.0,       # ref CULL_IDLE_TIME 1440m
        check_period: float = 60.0,           # ref IDLENESS_CHECK_PERIOD 1m
        clock=time.time,
        metrics=None,
    ):
        self.probe = probe
        self.enabled = enabled
        self.idle_time = idle_time
        self.check_period = check_period
        self.clock = clock
        self.metrics = metrics
        # Probe gate (the reference tracks a last-check timestamp for
        # the same reason, culler.go): our own annotation write emits a
        # MODIFIED watch event that re-enqueues this controller — without
        # the gate a busy notebook becomes a probe+write hot loop at
        # HTTP latency instead of one probe per check_period.
        self._last_probe: dict[tuple[str, str], float] = {}

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        try:
            nb = store.get("Notebook", namespace, name)
        except NotFound:
            self._last_probe.pop((namespace, name), None)
            return Result()
        assert isinstance(nb, Notebook)
        ann = nb.metadata.annotations
        if not self.enabled or STOP_ANNOTATION in ann:
            return Result(requeue_after=self.check_period)
        if ann.get(CULLING_DISABLED_ANNOTATION) == "true":
            return Result(requeue_after=self.check_period)

        now = self.clock()
        last_probe = self._last_probe.get((namespace, name))
        if last_probe is not None and now - last_probe < self.check_period:
            # Re-enqueued by a watch event (often our own write): not
            # due yet — skip the probe entirely so busy notebooks cost
            # one probe+write per check_period, not a hot loop.
            return Result(
                requeue_after=self.check_period - (now - last_probe))
        self._last_probe[(namespace, name)] = now
        if LAST_ACTIVITY_ANNOTATION not in ann:
            # First observation: initialize the activity clock (the
            # reference stamps the annotation at notebook creation) —
            # never cull based on an unrecorded past.
            self._annotate(store, namespace, name,
                           {LAST_ACTIVITY_ANNOTATION: str(now)})
            return Result(requeue_after=self.check_period)
        kernels = self.probe.kernels(namespace, name)
        last = float(ann.get(LAST_ACTIVITY_ANNOTATION, "0") or 0)

        if kernels is None:
            # Unreachable (starting/stopped): no state change (ref updates
            # only on successful probe, culler.go:266-300).
            return Result(requeue_after=self.check_period)

        busy = any(k.execution_state == "busy" for k in kernels)
        kernel_last = max((k.last_activity for k in kernels), default=0.0)
        # Terminal activity counts too (ref :357-382): an open shell
        # running a job must hold the notebook alive even with idle
        # kernels. Optional on the probe; never blocks on failure.
        term_fn = getattr(self.probe, "terminals", None)
        if term_fn is not None:
            stamps = term_fn(namespace, name)
            if stamps:
                kernel_last = max(kernel_last, max(stamps))
        prev = last
        if busy:
            last = now          # ref updateTimestampFromKernelsActivity :323-355
        else:
            last = max(last, kernel_last)
        if last != prev:
            # Only write on change: an unconditional update would emit a
            # MODIFIED watch event that re-enqueues this notebook and turns
            # the check_period poll into a hot loop.
            self._annotate(store, namespace, name,
                           {LAST_ACTIVITY_ANNOTATION: str(last)})

        if now - last > self.idle_time:     # ref NotebookNeedsCulling :405-420
            self._annotate(store, namespace, name, {
                STOP_ANNOTATION: _iso(now),  # ref SetStopAnnotation :118-141
            })
            store.emit_event(nb, "Normal", "Culled",
                             f"idle for {(now - last) / 60:.0f} min")
            if self.metrics is not None:
                self.metrics.notebook_culled.inc(namespace=namespace)
            log.info("culled notebook %s/%s", namespace, name)
        return Result(requeue_after=self.check_period)

    def _annotate(self, store: Store, namespace: str, name: str,
                  annotations: dict[str, str]) -> None:
        for _ in range(5):
            nb = store.try_get("Notebook", namespace, name)
            if nb is None:
                return
            nb.metadata.annotations.update(annotations)
            try:
                store.update(nb)
                return
            except Conflict:
                continue


def _iso(ts: float) -> str:
    import datetime

    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).isoformat()
