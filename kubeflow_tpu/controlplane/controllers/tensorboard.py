"""Tensorboard controller: Tensorboard CR → Deployment + Service + route.

Re-design of the reference's tensorboard-controller
(controllers/tensorboard_controller.go:67-149):
- logspath dispatch (generateDeployment :159-284):
    pvc://<name>/<subpath>  → mount that PVC at /logs (ref :170-223)
    gs://bucket/path        → mount the user-gcp-sa secret + pass the
                              GCS path straight to tensorboard (ref
                              :224-239) — the TPU-first default, since
                              TPU training writes Orbax/TensorBoard
                              events to GCS
    anything else           → legacy tb-volume PVC (ref :240+)
- image from TENSORBOARD_IMAGE env (ref :164); port 6006 (ref :273);
- VirtualService prefix /tensorboard/<ns>/<name>/ (ref :306-358);
- RWO-PVC co-scheduling via node affinity with the pod already mounting
  the PVC, gated by RWO_PVC_SCHEDULING (ref :408-451, :456-466) — the
  reference's only placement-aware code, kept because it generalizes to
  ICI-topology placement;
- Deployment conditions mirrored into CR status (ref :113-146).
"""

from __future__ import annotations

import os

from kubeflow_tpu.api.core import (
    Container,
    Deployment,
    DeploymentSpec,
    EnvVar,
    HTTPRoute,
    NodeSelectorTerm,
    PodTemplateSpec,
    Service,
    ServicePort,
    ServiceSpec,
    VirtualService,
    VirtualServiceSpec,
    Volume,
    VolumeMount,
)
from kubeflow_tpu.api.crds import Tensorboard
from kubeflow_tpu.controlplane.controllers.helpers import (
    copy_spec_and_labels,
    reconcile_child,
)
from kubeflow_tpu.controlplane.runtime import Controller, Result
from kubeflow_tpu.controlplane.store import NotFound, Store

DEFAULT_IMAGE = "tensorflow/tensorflow:2.16.1"   # env-overridable (ref :164)
TB_PORT = 6006
TB_NAME_LABEL = "tensorboard-name"


class TensorboardController(Controller):
    KIND = "Tensorboard"
    OWNS = ("Deployment", "Service", "VirtualService")

    def __init__(self, *, use_routing: bool = True,
                 rwo_pvc_scheduling: bool | None = None):
        self.use_routing = use_routing
        if rwo_pvc_scheduling is None:
            rwo_pvc_scheduling = (
                os.environ.get("RWO_PVC_SCHEDULING", "false") == "true"
            )
        self.rwo_pvc_scheduling = rwo_pvc_scheduling

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        try:
            tb = store.get("Tensorboard", namespace, name)
        except NotFound:
            return Result()
        assert isinstance(tb, Tensorboard)

        dep = self._desired_deployment(store, tb)
        reconcile_child(store, tb, dep, copy_spec_and_labels)
        svc = self._desired_service(tb)
        reconcile_child(store, tb, svc, copy_spec_and_labels)
        if self.use_routing:
            vs = self._desired_virtualservice(tb)
            reconcile_child(store, tb, vs, copy_spec_and_labels)

        cur_dep = store.try_get("Deployment", namespace, name)
        ready = bool(cur_dep and cur_dep.ready_replicas >= 1)
        conditions = list(cur_dep.conditions) if cur_dep else []
        fresh = store.try_get("Tensorboard", namespace, name)
        if fresh is not None and (fresh.status.ready != ready
                                  or fresh.status.conditions != conditions):
            fresh.status.ready = ready
            fresh.status.conditions = conditions
            store.update(fresh)
        return Result()

    def _desired_deployment(self, store: Store, tb: Tensorboard) -> Deployment:
        name, ns = tb.metadata.name, tb.metadata.namespace
        logspath = tb.spec.logspath
        volumes: list[Volume] = []
        mounts: list[VolumeMount] = []
        affinity: list[NodeSelectorTerm] = []
        logdir = logspath

        if logspath.startswith("pvc://"):
            rest = logspath[len("pvc://"):]
            pvc_name, _, sub_path = rest.partition("/")
            volumes.append(Volume(name="tb-logs", pvc_name=pvc_name))
            mounts.append(VolumeMount(name="tb-logs", mount_path="/logs",
                                      sub_path=sub_path))
            logdir = "/logs"
            if self.rwo_pvc_scheduling:
                affinity = self._rwo_affinity(store, ns, pvc_name)
        elif logspath.startswith("gs://"):
            # GCS-native (the TPU-first default): creds via secret mount
            volumes.append(Volume(name="gcp-creds", secret="user-gcp-sa"))
            mounts.append(VolumeMount(name="gcp-creds", mount_path="/secret/gcp"))
        else:
            volumes.append(Volume(name="tb-volume", pvc_name="tb-volume"))
            mounts.append(VolumeMount(name="tb-volume", mount_path="/logs",
                                      sub_path=logspath.lstrip("/")))
            logdir = "/logs"

        container = Container(
            name=name,
            image=os.environ.get("TENSORBOARD_IMAGE", DEFAULT_IMAGE),
            command=["/usr/local/bin/tensorboard"],
            args=[f"--logdir={logdir}", f"--port={TB_PORT}",
                  "--bind_all"],
            ports=[TB_PORT],
            volume_mounts=mounts,
        )
        if logspath.startswith("gs://"):
            container.env.append(EnvVar(
                "GOOGLE_APPLICATION_CREDENTIALS",
                "/secret/gcp/user-gcp-sa.json",
            ))

        dep = Deployment(
            spec=DeploymentSpec(
                replicas=1,
                selector={TB_NAME_LABEL: name},
                template=PodTemplateSpec(),
            )
        )
        dep.spec.template.metadata.labels = {TB_NAME_LABEL: name}
        dep.spec.template.spec.containers = [container]
        dep.spec.template.spec.volumes = volumes
        dep.spec.template.spec.affinity_terms = affinity
        dep.metadata.name = name
        dep.metadata.namespace = ns
        dep.metadata.labels = {TB_NAME_LABEL: name}
        return dep

    def _rwo_affinity(self, store: Store, namespace: str,
                      pvc_name: str) -> list[NodeSelectorTerm]:
        """Schedule next to the pod already mounting the RWO PVC
        (ref generateNodeAffinity :408-451: field-selector pod listing
        by claim)."""
        for pod in store.list("Pod", namespace):
            if any(v.pvc_name == pvc_name for v in pod.spec.volumes):
                if pod.host_ip:
                    return [NodeSelectorTerm(key="kubernetes.io/hostname",
                                             values=[pod.host_ip])]
        return []

    def _desired_service(self, tb: Tensorboard) -> Service:
        name, ns = tb.metadata.name, tb.metadata.namespace
        svc = Service(
            spec=ServiceSpec(
                selector={TB_NAME_LABEL: name},
                ports=[ServicePort("http", 80, TB_PORT)],
            )
        )
        svc.metadata.name = name
        svc.metadata.namespace = ns
        return svc

    def _desired_virtualservice(self, tb: Tensorboard) -> VirtualService:
        name, ns = tb.metadata.name, tb.metadata.namespace
        vs = VirtualService(
            spec=VirtualServiceSpec(
                gateways=["kubeflow-gateway"],
                hosts=["*"],
                http=[HTTPRoute(
                    prefix=f"/tensorboard/{ns}/{name}/",
                    rewrite="/",
                    destination_host=f"{name}.{ns}.svc",
                    destination_port=80,
                )],
            )
        )
        vs.metadata.name = f"tensorboard-{ns}-{name}"
        vs.metadata.namespace = ns
        return vs
