"""Create-or-update helpers with owned-field drift detection.

Pattern (not code) from the reference's common/reconcilehelper/util.go:
create if missing; if present, copy only the fields this controller owns
and update when they drifted (CopyStatefulSetFields :107-134,
CopyServiceFields :166-195 — which deliberately preserves clusterIP;
we preserve runtime-assigned fields the same way).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from kubeflow_tpu.api.core import Resource
from kubeflow_tpu.controlplane.store import NotFound, Store, set_controller_reference


def reconcile_child(
    store: Store,
    owner: Resource,
    desired: Resource,
    copy_fields: Callable[[Resource, Resource], bool],
) -> Resource:
    """Ensure `desired` exists and its owned fields match.

    `copy_fields(desired, current) -> changed` copies the owned fields
    onto `current` in place and reports drift.
    """
    set_controller_reference(owner, desired)
    try:
        current = store.get(desired.kind, desired.metadata.namespace,
                            desired.metadata.name)
    except NotFound:
        return store.create(desired)
    if copy_fields(desired, current):
        return store.update(current)
    return current


def copy_spec_and_labels(desired: Resource, current: Resource) -> bool:
    """Default owned-field copier: spec + labels/annotations we set.
    Runtime fields (status, uid, rv, clusterIP-style data) are preserved
    because only `spec`, labels and annotations are copied."""
    changed = False
    if dataclasses.asdict(desired.spec) != dataclasses.asdict(current.spec):  # type: ignore[attr-defined]
        current.spec = desired.spec  # type: ignore[attr-defined]
        changed = True
    for k, v in desired.metadata.labels.items():
        if current.metadata.labels.get(k) != v:
            current.metadata.labels[k] = v
            changed = True
    for k, v in desired.metadata.annotations.items():
        if current.metadata.annotations.get(k) != v:
            current.metadata.annotations[k] = v
            changed = True
    return changed
