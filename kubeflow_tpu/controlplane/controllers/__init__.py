"""Reconcilers (reference L2): notebook, workload, profile, tensorboard."""

from kubeflow_tpu.controlplane.controllers.notebook import NotebookController
from kubeflow_tpu.controlplane.controllers.workload import (
    DeploymentController,
    StatefulSetController,
    Scheduler,
    NodePool,
)
from kubeflow_tpu.controlplane.controllers.culler import Culler, ActivityProbe
from kubeflow_tpu.controlplane.controllers.profile import (
    ProfileController,
    WorkloadIdentityPlugin,
)
from kubeflow_tpu.controlplane.controllers.modelserver import (
    ModelServerController,
)
from kubeflow_tpu.controlplane.controllers.tensorboard import TensorboardController
