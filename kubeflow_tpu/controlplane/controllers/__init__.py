"""Reconcilers (reference L2): notebook, workload, profile, tensorboard."""

from kubeflow_tpu.controlplane.controllers.notebook import NotebookController
from kubeflow_tpu.controlplane.controllers.workload import (
    StatefulSetController,
    Scheduler,
    NodePool,
)
from kubeflow_tpu.controlplane.controllers.culler import Culler, ActivityProbe
