"""Notebook controller: Notebook CR → gang StatefulSet + headless Service
+ VirtualService; status mirroring; event re-emission.

TPU-first re-design of the reference's notebook-controller
(controllers/notebook_controller.go:90-282):
- the reference hard-codes a single-pod StatefulSet (replicas 0/1,
  generateStatefulSet :418-481); here replicas = number of TPU VM hosts
  in the slice topology (gang), one pod per host, each labeled with its
  gang ordinal so the admission webhook can compute TPU_WORKER_ID /
  TPU_WORKER_HOSTNAMES (webhook.py) — the NCCL-free multi-host bootstrap;
- Service is headless for stable per-host DNS (the reference's ClusterIP
  service :483-510 only needed one endpoint);
- VirtualService prefix `/notebook/<ns>/<name>/` and NB_PREFIX env kept
  (ref :516-610, :402-416) so notebook UIs behind a path proxy work;
- stop annotation ⇒ replicas 0 (ref :419-422, culler contract);
- pod warning events re-emitted onto the Notebook (ref :94-118) and pod
  state mirrored into status (ref :300-359).
"""

from __future__ import annotations

import os

from kubeflow_tpu.api.core import (
    Container,
    EnvVar,
    Event,
    HTTPRoute,
    Service,
    ServicePort,
    ServiceSpec,
    StatefulSet,
    StatefulSetSpec,
    VirtualService,
    VirtualServiceSpec,
)
from kubeflow_tpu.api.crds import (
    Notebook,
    NotebookCondition,
    STOP_ANNOTATION,
)
from kubeflow_tpu.controlplane.controllers.helpers import (
    copy_spec_and_labels,
    reconcile_child,
)
from kubeflow_tpu.controlplane.runtime import Controller, Result
from kubeflow_tpu.controlplane.store import NotFound, Store
from kubeflow_tpu.controlplane import webhook as wh
from kubeflow_tpu.parallel.mesh import SLICE_TOPOLOGIES

NOTEBOOK_NAME_LABEL = "notebook-name"       # ref notebook_controller.go:688-699
DEFAULT_PORT = 8888                          # ref :51
TPU_RESOURCE_KEY = "tpu/chips"
TOPOLOGY_NODE_SELECTOR = "kubeflow-tpu.dev/slice-topology"


class NotebookController(Controller):
    KIND = "Notebook"
    OWNS = ("StatefulSet", "Service", "VirtualService")
    WATCHES = ("Event",)   # re-emit pod/STS warnings onto the CR (ref :94-118)

    def __init__(self, *, use_routing: bool = True,
                 culling_check_period: float | None = None,
                 metrics=None):
        self.use_routing = use_routing
        # ref IDLENESS_CHECK_PERIOD (1m default) drives periodic requeue
        self.culling_check_period = culling_check_period
        self.metrics = metrics

    def watch_keys(self, obj):
        """Route an Event straight to the notebook it concerns: gang
        pods are '<nb>-<ordinal>', the STS carries the notebook's own
        name (ref SetupWithManager's event filtering,
        notebook_controller.go:703-723). Without this, every event in
        a namespace re-enqueued EVERY notebook in it — quadratic under
        a FailedScheduling storm."""
        if obj.kind != "Event":
            return None
        ns = obj.metadata.namespace
        name = obj.involved_name
        if obj.involved_kind == "Pod":
            base, _, ordinal = name.rpartition("-")
            return [(ns, base)] if base and ordinal.isdigit() else []
        if obj.involved_kind in ("StatefulSet", "Notebook"):
            return [(ns, name)]
        return []  # events on kinds this controller never mirrors

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        try:
            nb = store.get("Notebook", namespace, name)
        except NotFound:
            return Result()  # children garbage-collected via owner refs
        assert isinstance(nb, Notebook)

        topo_name = nb.spec.tpu.topology
        if topo_name and topo_name not in SLICE_TOPOLOGIES:
            # Surface the config error to the user instead of retrying
            # forever (the spawner UI mines warning events, ref
            # status.py:79-95).
            if not any(
                e.reason == "InvalidTopology"
                for e in store.events_for("Notebook", namespace, name)
            ):
                store.emit_event(
                    nb, "Warning", "InvalidTopology",
                    f"unknown TPU slice topology {topo_name!r}; known: "
                    f"{sorted(SLICE_TOPOLOGIES)}",
                )
            return Result()

        sts = self._desired_statefulset(nb)
        is_new = store.try_get("StatefulSet", namespace, name) is None
        reconcile_child(store, nb, sts, copy_spec_and_labels)
        if is_new and self.metrics is not None:
            # ref pkg/metrics/metrics.go created counter
            self.metrics.notebook_created.inc(namespace=namespace)
        svc = self._desired_service(nb)
        reconcile_child(store, nb, svc, copy_spec_and_labels)
        if self.use_routing:
            vs = self._desired_virtualservice(nb)
            reconcile_child(store, nb, vs, copy_spec_and_labels)

        self._mirror_status(store, nb)
        self._reemit_pod_events(store, nb)

        if self.culling_check_period:
            return Result(requeue_after=self.culling_check_period)
        return Result()

    # -- desired children --------------------------------------------------

    def _gang_size(self, nb: Notebook) -> int:
        topo_name = nb.spec.tpu.topology
        if not topo_name:
            return 1
        topo = SLICE_TOPOLOGIES[topo_name]
        # Multi-slice jobs gang ALL slices' hosts into one StatefulSet:
        # ordinals [0, hosts) are slice 0, [hosts, 2*hosts) slice 1, ...
        # (the webhook derives per-slice worker ids + MEGASCALE env from
        # the ordinal).
        return topo.hosts * max(1, nb.spec.tpu.num_slices)

    def _desired_statefulset(self, nb: Notebook) -> StatefulSet:
        name, ns = nb.metadata.name, nb.metadata.namespace
        stopped = STOP_ANNOTATION in nb.metadata.annotations  # ref :419-422
        gang_size = self._gang_size(nb)
        replicas = 0 if stopped else gang_size

        template = nb.spec.template
        tmpl = template.__class__(
            metadata=template.metadata.__class__(
                labels={
                    **template.metadata.labels,
                    NOTEBOOK_NAME_LABEL: name,
                    wh.GANG_NAME_LABEL: name,
                    wh.GANG_SIZE_LABEL: str(gang_size),
                },
                annotations=dict(template.metadata.annotations),
            ),
            spec=template.spec,
        )
        tmpl = _clone(tmpl)
        topo_name = nb.spec.tpu.topology
        if topo_name:
            tmpl.metadata.labels[wh.TOPOLOGY_LABEL] = topo_name
            if nb.spec.tpu.num_slices > 1:
                tmpl.metadata.labels[wh.NUM_SLICES_LABEL] = str(
                    nb.spec.tpu.num_slices
                )
            if nb.spec.tpu.mesh:
                tmpl.metadata.labels[wh.MESH_LABEL] = (
                    nb.spec.tpu.mesh.replace(",", "_")
                )
            topo = SLICE_TOPOLOGIES[topo_name]
            # ICI-topology-aware placement: pin to the right slice pool
            # (generalizes the reference's only placement-aware code, the
            # RWO-PVC affinity in tensorboard_controller.go:408-451).
            tmpl.spec.node_selector.setdefault(TOPOLOGY_NODE_SELECTOR, topo_name)
            for c in tmpl.spec.containers:
                c.resources.limits.setdefault(
                    TPU_RESOURCE_KEY, str(topo.chips_per_host)
                )

        if not tmpl.spec.containers:
            tmpl.spec.containers.append(Container(name=name))
        main = tmpl.spec.containers[0]
        if not any(p == DEFAULT_PORT for p in main.ports):
            main.ports.append(DEFAULT_PORT)
        # NB_PREFIX env for path-proxied UIs (ref :402-416)
        if not any(e.name == "NB_PREFIX" for e in main.env):
            main.env.append(EnvVar("NB_PREFIX", f"/notebook/{ns}/{name}"))
        if tmpl.spec.fs_group is None and os.environ.get("ADD_FSGROUP", "true") != "false":
            tmpl.spec.fs_group = 100  # ref :468-479

        sts = StatefulSet(
            spec=StatefulSetSpec(
                replicas=replicas,
                service_name=name,
                selector={NOTEBOOK_NAME_LABEL: name},
                template=tmpl,
                gang=gang_size > 1,
            )
        )
        sts.metadata.name = name
        sts.metadata.namespace = ns
        sts.metadata.labels = {NOTEBOOK_NAME_LABEL: name}
        return sts

    def _desired_service(self, nb: Notebook) -> Service:
        name, ns = nb.metadata.name, nb.metadata.namespace
        svc = Service(
            spec=ServiceSpec(
                selector={NOTEBOOK_NAME_LABEL: name},
                ports=[ServicePort("http", 80, DEFAULT_PORT)],
                headless=True,   # stable per-host DNS for the gang
            )
        )
        svc.metadata.name = name
        svc.metadata.namespace = ns
        svc.metadata.labels = {NOTEBOOK_NAME_LABEL: name}
        return svc

    def _desired_virtualservice(self, nb: Notebook) -> VirtualService:
        name, ns = nb.metadata.name, nb.metadata.namespace
        prefix = f"/notebook/{ns}/{name}/"   # ref :53-54, :516-610
        vs = VirtualService(
            spec=VirtualServiceSpec(
                gateways=["kubeflow-gateway"],
                hosts=["*"],
                http=[
                    HTTPRoute(
                        prefix=prefix,
                        rewrite="/",
                        destination_host=f"{name}.{ns}.svc",
                        destination_port=80,
                    )
                ],
            )
        )
        vs.metadata.name = f"notebook-{ns}-{name}"
        vs.metadata.namespace = ns
        return vs

    # -- status + events ---------------------------------------------------

    def _mirror_status(self, store: Store, nb: Notebook) -> None:
        pods = store.list(
            "Pod", nb.metadata.namespace,
            label_selector={NOTEBOOK_NAME_LABEL: nb.metadata.name},
        )
        ready = sum(1 for p in pods if p.phase == "Running" and p.ready)
        # One namespace-wide event scan per reconcile (not per pod):
        # _mirror_status runs on every pod/STS watch event, so per-pod
        # events_for calls would be O(pods x events) on the hot path.
        # Keep the LATEST warning per object by timestamp — store.list
        # orders events by name (random uuid suffix), not recency.
        warnings_by_obj: dict[tuple[str, str], Event] = {}
        for e in store.list("Event", nb.metadata.namespace):
            if e.type != "Warning":
                continue
            key = (e.involved_kind, e.involved_name)
            prev = warnings_by_obj.get(key)
            if prev is None or e.timestamp >= prev.timestamp:
                warnings_by_obj[key] = e
        state = ""
        conditions = []
        for p in sorted(pods, key=lambda p: p.metadata.name):
            state = state or (
                "running" if p.phase == "Running" else
                "terminated" if p.phase in ("Succeeded", "Failed") else "waiting"
            )
            # Mirror WHY a pod is stuck, not just its phase — the spawner
            # UI's "why is my pod pending" depends on it (ref
            # notebook_controller.go:300-359 mirrors container
            # state/reason; here the explanation lives in the pod's
            # Warning events, e.g. FailedScheduling from the gang
            # scheduler).
            reason = message = ""
            if p.phase not in ("Running", "Succeeded"):
                last = warnings_by_obj.get(("Pod", p.metadata.name))
                if last is not None:
                    reason, message = last.reason, last.message
            conditions.append(NotebookCondition(
                type=p.phase, reason=reason, message=message,
            ))
        if not pods:
            # Gang scheduling failures create no pods at all; the warning
            # sits on the StatefulSet. Surface it so status explains the
            # empty gang instead of showing nothing.
            last = warnings_by_obj.get(("StatefulSet", nb.metadata.name))
            if last is not None:
                state = "waiting"
                conditions.append(NotebookCondition(
                    type="Pending", reason=last.reason, message=last.message,
                ))
        fresh = store.try_get("Notebook", nb.metadata.namespace, nb.metadata.name)
        if fresh is None:
            return
        assert isinstance(fresh, Notebook)

        def _key(cs):
            return [(c.type, c.reason, c.message) for c in cs]

        if (fresh.status.ready_replicas, fresh.status.container_state,
                _key(fresh.status.conditions)) != (ready, state,
                                                   _key(conditions)):
            fresh.status.ready_replicas = ready
            fresh.status.container_state = state
            fresh.status.conditions = conditions
            store.update(fresh)

    def _reemit_pod_events(self, store: Store, nb: Notebook) -> None:
        """Surface pod warnings on the Notebook (ref :94-118, predicate
        :703-723 filters to warning/scheduling events)."""
        ns, name = nb.metadata.namespace, nb.metadata.name
        existing = {
            (e.reason, e.message)
            for e in store.events_for("Notebook", ns, name)
        }
        sources = [
            ev
            for pod in store.list("Pod", ns,
                                  label_selector={NOTEBOOK_NAME_LABEL: name})
            for ev in store.events_for("Pod", ns, pod.metadata.name)
        ] + store.events_for("StatefulSet", ns, name)
        for ev in sources:
            if ev.type != "Warning":
                continue
            if (ev.reason, ev.message) in existing:
                continue
            store.emit_event(nb, "Warning", ev.reason, ev.message)
            existing.add((ev.reason, ev.message))


def _clone(obj):
    import copy

    return copy.deepcopy(obj)
