"""Profile controller: multi-tenancy with first-class TPU quota.

Re-design of the reference's profile-controller
(controllers/profile_controller.go:105-322):
- cluster-scoped Profile → owned Namespace with owner annotation
  (:127-198) and default labels;
- AuthorizationPolicy allowing the owner's identity header, in-namespace
  traffic, and the notebook-controller's kernels-probe path (:407-524);
- `default-editor` / `default-viewer` ServiceAccounts with RoleBindings
  (:560-639) plus the owner's admin RoleBinding (:230-251);
- ResourceQuota from spec (:526-557) — TPU-first: `tpu/<gen>-chips`
  quota keys are validated against the slice-topology table so a tenant
  can be capped at e.g. 32 v5e chips;
- pluggable cloud-identity plugins (:643-701, plugin_workload_identity.
  go:44-51): here an in-memory WorkloadIdentity plugin annotates the
  editor SA (pure policy editing, testable like plugin_iam_test.go);
- finalizer-based cleanup (:284-319): deleting the Profile deletes the
  namespace and everything in it.
"""

from __future__ import annotations

import logging
from typing import Protocol

from kubeflow_tpu.api.core import (
    AuthorizationPolicy,
    Namespace,
    ResourceQuota,
    RoleBinding,
    ServiceAccount,
)
from kubeflow_tpu.api.crds import PROFILE_FINALIZER, Profile
from kubeflow_tpu.controlplane.runtime import Controller, Result
from kubeflow_tpu.controlplane.store import (
    AlreadyExists,
    NotFound,
    Store,
    set_controller_reference,
)

log = logging.getLogger(__name__)

OWNER_ANNOTATION = "kubeflow-tpu.dev/profile-owner"
ROLE_ADMIN = "kubeflow-tpu-admin"
ROLE_EDIT = "kubeflow-tpu-edit"
ROLE_VIEW = "kubeflow-tpu-view"
KERNELS_PROBE_PATH = "/notebook/*/*/api/kernels"   # culler probe allowance


class ProfilePlugin(Protocol):
    """ref Plugin iface profile_controller.go:77-83."""

    def apply(self, store: Store, profile: Profile) -> None: ...
    def revoke(self, store: Store, profile: Profile) -> None: ...


class WorkloadIdentityPlugin:
    """Binds the namespace's editor SA to a cloud service account by
    annotation (ref plugin_workload_identity.go:34-51: annotation
    `iam.gke.io/gcp-service-account`). Pure metadata editing — the cloud
    IAM call is out of scope exactly as the reference's tests treat it."""

    SA_ANNOTATION = "iam.kubeflow-tpu.dev/gcp-service-account"

    def __init__(self, gsa_format: str = "{profile}@project.iam.gserviceaccount.com"):
        self.gsa_format = gsa_format

    def with_options(self, options: dict[str, str]) -> "WorkloadIdentityPlugin":
        """Per-profile configuration (ref GetPluginSpec unmarshalling the
        CR's plugin spec into the plugin struct)."""
        if not options:
            return self
        return WorkloadIdentityPlugin(
            gsa_format=options.get("gsaFormat", self.gsa_format))

    def apply(self, store: Store, profile: Profile) -> None:
        ns = profile.metadata.name
        sa = store.try_get("ServiceAccount", ns, "default-editor")
        if sa is None:
            return
        gsa = self.gsa_format.format(profile=profile.metadata.name)
        if sa.metadata.annotations.get(self.SA_ANNOTATION) != gsa:
            sa.metadata.annotations[self.SA_ANNOTATION] = gsa
            store.update(sa)

    def revoke(self, store: Store, profile: Profile) -> None:
        ns = profile.metadata.name
        sa = store.try_get("ServiceAccount", ns, "default-editor")
        if sa is None:
            return
        if self.SA_ANNOTATION in sa.metadata.annotations:
            del sa.metadata.annotations[self.SA_ANNOTATION]
            store.update(sa)


class IamForServiceAccountPlugin:
    """AWS-IRSA-equivalent: edits a role trust policy (in-memory JSON,
    exactly the scope the reference tests — plugin_iam.go:134-248 /
    plugin_iam_test.go operate on policy documents without AWS calls) and
    annotates the editor SA with the role ARN
    (ref annotation `eks.amazonaws.com/role-arn`, plugin_iam.go:24)."""

    SA_ANNOTATION = "iam.kubeflow-tpu.dev/role-arn"

    def __init__(self, *, role_arn_format: str =
                 "arn:aws:iam::0:role/{profile}",
                 oidc_provider: str = "oidc.example.com/id/CLUSTER",
                 policies: dict[str, dict] | None = None):
        self.role_arn_format = role_arn_format
        self.oidc_provider = oidc_provider
        # role arn -> trust policy document (the fake IAM backend).
        self.policies: dict[str, dict] = policies if policies is not None else {}

    def with_options(self, options: dict[str, str]) -> "IamForServiceAccountPlugin":
        """Per-profile configuration; the policy store is SHARED with the
        registry instance so apply/revoke see the same IAM state."""
        if not options:
            return self
        return IamForServiceAccountPlugin(
            role_arn_format=options.get("roleArnFormat",
                                        self.role_arn_format),
            oidc_provider=options.get("oidcProvider", self.oidc_provider),
            policies=self.policies,
        )

    def _subject(self, profile: Profile) -> str:
        return (f"system:serviceaccount:{profile.metadata.name}:"
                f"default-editor")

    def apply(self, store: Store, profile: Profile) -> None:
        arn = self.role_arn_format.format(profile=profile.metadata.name)
        policy = self.policies.setdefault(
            arn, {"Version": "2012-10-17", "Statement": []})
        add_irsa_statement(policy, self.oidc_provider,
                           self._subject(profile))
        sa = store.try_get("ServiceAccount", profile.metadata.name,
                           "default-editor")
        if sa is not None and sa.metadata.annotations.get(
            self.SA_ANNOTATION
        ) != arn:
            sa.metadata.annotations[self.SA_ANNOTATION] = arn
            store.update(sa)

    def revoke(self, store: Store, profile: Profile) -> None:
        arn = self.role_arn_format.format(profile=profile.metadata.name)
        policy = self.policies.get(arn)
        if policy is not None:
            remove_irsa_statement(policy, self.oidc_provider,
                                  self._subject(profile))
        sa = store.try_get("ServiceAccount", profile.metadata.name,
                           "default-editor")
        if sa is not None and self.SA_ANNOTATION in sa.metadata.annotations:
            del sa.metadata.annotations[self.SA_ANNOTATION]
            store.update(sa)


def _irsa_condition_key(oidc_provider: str) -> str:
    return f"{oidc_provider}:sub"


def add_irsa_statement(policy: dict, oidc_provider: str,
                       subject: str) -> None:
    """Idempotently grant `subject` AssumeRoleWithWebIdentity via the
    OIDC provider. Mirrors the reference's trust-policy editing semantics
    (plugin_iam.go:134-248): one statement per provider, subjects
    accumulate in the StringEquals condition (string or list form)."""
    stmts = policy.setdefault("Statement", [])
    key = _irsa_condition_key(oidc_provider)
    for s in stmts:
        cond = s.get("Condition", {}).get("StringEquals", {})
        if key in cond:
            subs = cond[key]
            if isinstance(subs, str):
                if subs == subject:
                    return
                cond[key] = [subs, subject]
            elif subject not in subs:
                subs.append(subject)
            return
    stmts.append({
        "Effect": "Allow",
        "Principal": {"Federated": oidc_provider},
        "Action": "sts:AssumeRoleWithWebIdentity",
        "Condition": {"StringEquals": {key: subject}},
    })


def remove_irsa_statement(policy: dict, oidc_provider: str,
                          subject: str) -> None:
    """Remove `subject`; drops the whole statement when it was the last
    subject (ref plugin_iam.go deletion path)."""
    stmts = policy.get("Statement", [])
    key = _irsa_condition_key(oidc_provider)
    for s in list(stmts):
        cond = s.get("Condition", {}).get("StringEquals", {})
        if key not in cond:
            continue
        subs = cond[key]
        if isinstance(subs, str):
            if subs == subject:
                stmts.remove(s)
        else:
            if subject in subs:
                subs.remove(subject)
            if len(subs) == 1:
                cond[key] = subs[0]
            elif not subs:
                stmts.remove(s)
        return


PLUGIN_KINDS: dict[str, type] = {
    "WorkloadIdentity": WorkloadIdentityPlugin,
    "IamForServiceAccount": IamForServiceAccountPlugin,
}


def resolve_profile_plugins(
    profile: Profile,
    registry: dict[str, "ProfilePlugin"],
) -> list["ProfilePlugin"]:
    """Per-profile plugin resolution (ref GetPluginSpec
    profile_controller.go:643-675): the Profile CR names its plugins;
    instances come from the controller's registry so state (fake IAM
    policies, formats) is shared across profiles."""
    out = []
    for ps in profile.spec.plugins:
        plugin = registry.get(ps.kind)
        if plugin is None:
            raise ValueError(
                f"profile {profile.metadata.name}: unknown plugin kind "
                f"{ps.kind!r} (have {sorted(registry)})")
        if ps.options:
            configure = getattr(plugin, "with_options", None)
            if configure is None:
                raise ValueError(
                    f"profile {profile.metadata.name}: plugin {ps.kind!r} "
                    "does not accept options")
            plugin = configure(dict(ps.options))
        out.append(plugin)
    return out


class ProfileController(Controller):
    KIND = "Profile"
    OWNS = ("Namespace",)

    def __init__(self, *, default_namespace_labels: dict[str, str] | None = None,
                 plugins: list[ProfilePlugin] | None = None,
                 plugin_registry: dict[str, ProfilePlugin] | None = None):
        # ref: fsnotify-watched labels file (profile_controller.go:356-405);
        # our config layer (utils/config.py) hot-reloads and re-creates the
        # controller-visible dict in place.
        self.default_namespace_labels = default_namespace_labels or {}
        self.plugins = plugins or []          # applied to every profile
        # kind -> instance, consulted for Profile.spec.plugins entries
        # (ref GetPluginSpec). Default registry has both cloud plugins.
        self.plugin_registry = (
            plugin_registry if plugin_registry is not None
            else {k: cls() for k, cls in PLUGIN_KINDS.items()})

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        try:
            profile = store.get("Profile", "", name)
        except NotFound:
            return Result()
        assert isinstance(profile, Profile)

        # Defense in depth vs privilege escalation: a profile that would
        # own a reserved/system namespace never reconciles (Kfam rejects
        # these too, but direct CR creation must not bypass it).
        from kubeflow_tpu.controlplane.auth import is_reserved_namespace

        if is_reserved_namespace(name):
            if profile.status.phase != "Failed":
                profile.status.phase = "Failed"
                profile.status.message = f"namespace name {name!r} is reserved"
                store.update(profile)
            return Result()

        if profile.metadata.deletion_timestamp is not None:
            return self._finalize(store, profile)

        if PROFILE_FINALIZER not in profile.metadata.finalizers:
            profile.metadata.finalizers.append(PROFILE_FINALIZER)
            store.update(profile)
            return Result()  # re-enqueued by our own MODIFIED event

        # Serving-QoS bridge: the `kubeflow-tpu.dev/serving-tenant`
        # annotation becomes a data-plane tenant spec
        # (tenancy.config_from_profiles), so a malformed one must fail
        # HERE at reconcile time — not later inside a serving process
        # that loads tenant configs from Profiles.
        from kubeflow_tpu.tenancy import tenant_from_profile

        try:
            tenant_from_profile(profile)
        except ValueError as e:
            fresh = store.try_get("Profile", "", name)
            if fresh is not None and fresh.status.message != str(e):
                fresh.status.phase = "Failed"
                fresh.status.message = str(e)
                store.update(fresh)
            return Result()

        if not self._ensure_namespace(store, profile):
            return Result()  # ownership conflict surfaced in status
        self._ensure_service_accounts(store, profile)
        self._ensure_role_bindings(store, profile)
        self._ensure_authorization_policy(store, profile)
        self._ensure_quota(store, profile)
        try:
            per_profile = resolve_profile_plugins(
                profile, self.plugin_registry)
        except ValueError as e:
            fresh = store.try_get("Profile", "", name)
            if fresh is not None and fresh.status.message != str(e):
                fresh.status.phase = "Failed"
                fresh.status.message = str(e)
                store.update(fresh)
            return Result()
        for plugin in [*self.plugins, *per_profile]:
            plugin.apply(store, profile)

        fresh = store.try_get("Profile", "", name)
        if fresh is not None and fresh.status.phase != "Ready":
            fresh.status.phase = "Ready"
            fresh.status.message = ""
            store.update(fresh)
        return Result()

    # -- pieces ------------------------------------------------------------

    def _ensure_namespace(self, store: Store, profile: Profile) -> bool:
        name = profile.metadata.name
        existing = store.try_get("Namespace", "", name)
        if existing is None:
            ns = Namespace()
            ns.metadata.name = name
            ns.metadata.annotations[OWNER_ANNOTATION] = profile.spec.owner
            ns.metadata.labels.update(self.default_namespace_labels)
            set_controller_reference(profile, ns)
            try:
                store.create(ns)
            except AlreadyExists:
                pass
            return True
        # Ownership check (ref :127-198): namespace created by someone else
        # is NOT adopted.
        owner = existing.metadata.annotations.get(OWNER_ANNOTATION)
        if owner != profile.spec.owner:
            fresh = store.try_get("Profile", "", name)
            if fresh is not None and fresh.status.phase != "Failed":
                fresh.status.phase = "Failed"
                fresh.status.message = (
                    f"namespace {name} exists and is not owned by "
                    f"{profile.spec.owner}"
                )
                store.update(fresh)
            return False
        # label merge semantics (ref setNamespaceLabels :722-741:
        # empty value ⇒ delete label)
        changed = False
        for k, v in self.default_namespace_labels.items():
            if v == "" and k in existing.metadata.labels:
                del existing.metadata.labels[k]
                changed = True
            elif v != "" and existing.metadata.labels.get(k) != v:
                existing.metadata.labels[k] = v
                changed = True
        if changed:
            store.update(existing)
        return True

    def _ensure_service_accounts(self, store: Store, profile: Profile) -> None:
        ns = profile.metadata.name
        for sa_name in ("default-editor", "default-viewer"):
            if store.try_get("ServiceAccount", ns, sa_name) is None:
                sa = ServiceAccount()
                sa.metadata.name = sa_name
                sa.metadata.namespace = ns
                try:
                    store.create(sa)
                except AlreadyExists:
                    pass

    def _ensure_role_bindings(self, store: Store, profile: Profile) -> None:
        ns = profile.metadata.name
        wanted = [
            ("default-editor", ROLE_EDIT, [f"sa:{ns}:default-editor"]),
            ("default-viewer", ROLE_VIEW, [f"sa:{ns}:default-viewer"]),
            ("namespace-admin", ROLE_ADMIN, [profile.spec.owner]),
        ]
        for rb_name, role, subjects in wanted:
            existing = store.try_get("RoleBinding", ns, rb_name)
            if existing is None:
                # No user/role annotations: those mark KFAM-managed
                # contributor bindings only (KFAM lists bindings back from
                # annotations, ref bindings.go:179-222).
                rb = RoleBinding(role=role, subjects=subjects)
                rb.metadata.name = rb_name
                rb.metadata.namespace = ns
                try:
                    store.create(rb)
                except AlreadyExists:
                    pass
            elif existing.role != role or existing.subjects != subjects:
                existing.role = role
                existing.subjects = subjects
                store.update(existing)

    def _ensure_authorization_policy(self, store: Store, profile: Profile) -> None:
        ns = profile.metadata.name
        desired_users = sorted({
            u for rb in store.list("RoleBinding", ns) for u in rb.subjects
        } | {profile.spec.owner})
        existing = store.try_get("AuthorizationPolicy", ns, "ns-owner-access")
        if existing is None:
            ap = AuthorizationPolicy(
                allow_users=desired_users,
                allow_namespaces=[ns],          # in-ns traffic (ref :452-469)
                allow_paths=[KERNELS_PROBE_PATH],
            )
            ap.metadata.name = "ns-owner-access"
            ap.metadata.namespace = ns
            try:
                store.create(ap)
            except AlreadyExists:
                pass
        elif existing.allow_users != desired_users:
            existing.allow_users = desired_users
            store.update(existing)

    def _ensure_quota(self, store: Store, profile: Profile) -> None:
        ns = profile.metadata.name
        if not profile.spec.resource_quota:
            return
        existing = store.try_get("ResourceQuota", ns, "kf-resource-quota")
        if existing is None:
            rq = ResourceQuota(hard=dict(profile.spec.resource_quota))
            rq.metadata.name = "kf-resource-quota"
            rq.metadata.namespace = ns
            try:
                store.create(rq)
            except AlreadyExists:
                pass
        elif existing.hard != profile.spec.resource_quota:
            existing.hard = dict(profile.spec.resource_quota)
            store.update(existing)

    def _finalize(self, store: Store, profile: Profile) -> Result:
        # Revoke every kind that is still resolvable — one unknown kind
        # must not leak the others' external state (IAM trust policies
        # are not cleaned up by the namespace cascade).
        per_profile = []
        for ps in profile.spec.plugins:
            known = Profile()
            known.metadata.name = profile.metadata.name
            known.spec.owner = profile.spec.owner
            known.spec.plugins = [ps]
            try:
                per_profile.extend(
                    resolve_profile_plugins(known, self.plugin_registry))
            except ValueError:
                continue  # unknown kind: nothing we can revoke
        for plugin in [*self.plugins, *per_profile]:
            plugin.revoke(store, profile)
        try:
            store.delete("Namespace", "", profile.metadata.name)
        except NotFound:
            pass
        fresh = store.try_get("Profile", "", profile.metadata.name)
        if fresh is not None and PROFILE_FINALIZER in fresh.metadata.finalizers:
            fresh.metadata.finalizers.remove(PROFILE_FINALIZER)
            store.update(fresh)
        return Result()

