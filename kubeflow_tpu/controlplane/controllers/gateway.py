"""Gateway layer: auth-proxy injection, edge Routes, NetworkPolicies,
reconciliation lock — the second operator over the same Notebook CR.

Re-design (capability parity, new mechanism) of the reference's
odh-notebook-controller:
- a Notebook-level mutating webhook (ref notebook_webhook.go:226-265)
  that, at create, (a) injects a reconciliation lock — the culler's stop
  annotation reused as a startup gate so the pod cannot start before its
  pull secret / auth material exists (ref InjectReconciliationLock
  notebook_webhook.go:55-64), (b) injects an auth-proxy sidecar when
  annotated (ref InjectOAuthProxy :68-223: SAR-gated proxy on :8443,
  100m/64Mi requests=limits, health probes, cookie+TLS secret volumes,
  dedicated ServiceAccount), and (c) injects cluster-wide egress-proxy
  env + trusted CA bundle (ref InjectProxyConfig :299-398);
- a second controller on the Notebook kind reconciling the objects the
  sidecar needs — ServiceAccount, tls Service, cookie Secret, Route,
  NetworkPolicies, trusted-CA ConfigMap (ref notebook_oauth.go:74-262,
  notebook_route.go:34-146, notebook_network.go:42-221) — and removing
  the lock once the ServiceAccount's pull secret is visible, with
  bounded retry (ref RemoveReconciliationLock notebook_controller.go:94-122).

TPU-native framing: on GKE there is no OpenShift OAuth server; the same
capability is a SAR-gated identity-aware proxy sidecar in front of the
notebook (IAP-style), and `Route` maps to a gateway HTTPRoute. The gate
matters MORE on TPU slices than it did upstream: a gang pod that starts
before its neighbors' auth material exists wedges the whole slice's
`jax.distributed.initialize` barrier, so the lock holds replicas at 0
until the control plane is ready for the entire gang.
"""

from __future__ import annotations

import secrets as pysecrets
import time

from kubeflow_tpu.api.core import (
    ConfigMap,
    Container,
    EnvVar,
    NetworkPolicy,
    Pod,
    Probe,
    Resource,
    ResourceRequirements,
    Route,
    Secret,
    Service,
    ServiceAccount,
    ServicePort,
    ServiceSpec,
    Volume,
    VolumeMount,
)
from kubeflow_tpu.api.crds import Notebook, STOP_ANNOTATION
from kubeflow_tpu.controlplane.controllers.helpers import (
    copy_spec_and_labels,
    reconcile_child,
)
from kubeflow_tpu.controlplane.controllers.notebook import DEFAULT_PORT
from kubeflow_tpu.controlplane.runtime import Controller, Result
from kubeflow_tpu.controlplane.store import NotFound, Store, set_controller_reference

# Annotations (ref odh-notebook-controller const block):
INJECT_AUTH_PROXY_ANNOTATION = "kubeflow-tpu.dev/inject-auth-proxy"
LOGOUT_URL_ANNOTATION = "kubeflow-tpu.dev/logout-url"
# Lock value distinguishes "stopped by the gateway's startup gate" from a
# user/culler stop (ref AnnotationValueReconciliationLock).
LOCK_VALUE = "gateway-lock"

AUTH_PROXY_CONTAINER = "auth-proxy"
AUTH_PROXY_PORT = 8443                     # ref notebook_network.go:36
AUTH_PROXY_PORT_NAME = "auth-proxy"
AUTH_SERVICE_PORT = 443                    # ref notebook_oauth.go:36
DEFAULT_PROXY_IMAGE = "kubeflow-tpu/auth-proxy:latest"

# Cluster-wide egress proxy config lives in this ConfigMap (the reference
# reads the OpenShift cluster Proxy resource, notebook_webhook.go:267-297).
SYSTEM_NAMESPACE = "kubeflow-tpu-system"
CLUSTER_PROXY_CONFIGMAP = "cluster-proxy-config"
TRUSTED_CA_CONFIGMAP = "trusted-ca-bundle"

# Bounded wait for the pull secret before force-unlocking (the reference
# retries 1s+5s+25s with backoff then removes the lock regardless,
# notebook_controller.go:94-122). Wall-clock budget, not a retry count:
# watch events re-enqueue reconciles faster than any requeue delay, so a
# counter would burn its retries in milliseconds.
LOCK_WAIT_BUDGET = 31.0


def auth_enabled(nb: Notebook) -> bool:
    return nb.metadata.annotations.get(
        INJECT_AUTH_PROXY_ANNOTATION, ""
    ).lower() in ("1", "true")


def locked(nb: Notebook) -> bool:
    return nb.metadata.annotations.get(STOP_ANNOTATION) == LOCK_VALUE


class NotebookGatewayWebhook:
    """Mutating webhook on Notebook create (register on the store).

    The reference mounts this at /mutate-notebook-v1 and handles
    create+update; our store runs mutators at create, which covers both
    injections that matter (the lock is create-only upstream too,
    notebook_webhook.go:234-240).
    """

    def __init__(self, store: Store, *, proxy_image: str = DEFAULT_PROXY_IMAGE,
                 enable_lock: bool = True):
        self.store = store
        self.proxy_image = proxy_image
        self.enable_lock = enable_lock

    def __call__(self, obj: Resource) -> None:
        if not isinstance(obj, Notebook):
            return
        if self.enable_lock and STOP_ANNOTATION not in obj.metadata.annotations:
            obj.metadata.annotations[STOP_ANNOTATION] = LOCK_VALUE
        if auth_enabled(obj):
            inject_auth_proxy(obj, self.proxy_image)
        proxy_env = cluster_proxy_env(self.store)
        if proxy_env:
            inject_proxy_config(obj, proxy_env)


def inject_auth_proxy(nb: Notebook, image: str) -> None:
    """Add (or replace) the SAR-gated identity proxy sidecar.

    Mirrors ref InjectOAuthProxy (notebook_webhook.go:68-223): the proxy
    terminates TLS on :8443, checks a SubjectAccessReview on the Notebook
    resource itself, then forwards to the Jupyter port on localhost.
    """
    name, ns = nb.metadata.name, nb.metadata.namespace
    args = [
        "--provider=kubernetes-sar",
        f"--https-address=:{AUTH_PROXY_PORT}",
        f"--service-account={name}",
        "--cookie-secret-file=/etc/auth/config/cookie_secret",
        "--cookie-expire=24h0m0s",
        "--tls-cert=/etc/tls/private/tls.crt",
        "--tls-key=/etc/tls/private/tls.key",
        f"--upstream=http://localhost:{DEFAULT_PORT}",
        "--email-domain=*",
        "--skip-provider-button",
        (
            '--sar={"verb":"get","resource":"notebooks",'
            f'"resourceName":"{name}","namespace":"{ns}"}}'
        ),
    ]
    logout = nb.metadata.annotations.get(LOGOUT_URL_ANNOTATION, "")
    if logout:
        args.append(f"--logout-url={logout}")
    sidecar = Container(
        name=AUTH_PROXY_CONTAINER,
        image=image,
        args=args,
        ports=[AUTH_PROXY_PORT],
        env=[EnvVar("NAMESPACE", ns)],
        volume_mounts=[
            VolumeMount(name="auth-config", mount_path="/etc/auth/config"),
            VolumeMount(name="tls-certificates", mount_path="/etc/tls/private"),
        ],
        resources=ResourceRequirements(
            requests={"cpu": "100m", "memory": "64Mi"},   # ref :131-140
            limits={"cpu": "100m", "memory": "64Mi"},
        ),
        liveness_probe=Probe(path="/auth/healthz", port=AUTH_PROXY_PORT,
                             initial_delay_seconds=30, period_seconds=5),
        readiness_probe=Probe(path="/auth/healthz", port=AUTH_PROXY_PORT,
                              initial_delay_seconds=5, period_seconds=5),
    )
    spec = nb.spec.template.spec
    for i, c in enumerate(spec.containers):
        if c.name == AUTH_PROXY_CONTAINER:
            spec.containers[i] = sidecar
            break
    else:
        spec.containers.append(sidecar)
    _upsert_volume(spec.volumes, Volume(name="auth-config",
                                        secret=f"{name}-auth-config"))
    _upsert_volume(spec.volumes, Volume(name="tls-certificates",
                                        secret=f"{name}-tls"))
    # Dedicated ServiceAccount, never `default` (ref :221-222).
    spec.service_account = name


def _upsert_volume(volumes: list[Volume], vol: Volume) -> None:
    for i, v in enumerate(volumes):
        if v.name == vol.name:
            volumes[i] = vol
            return
    volumes.append(vol)


def cluster_proxy_env(store: Store) -> dict[str, str]:
    """Egress-proxy env from the cluster config (ref ClusterWideProxyIsEnabled
    + InjectProxyConfig, notebook_webhook.go:267-398)."""
    cm = store.try_get("ConfigMap", SYSTEM_NAMESPACE, CLUSTER_PROXY_CONFIGMAP)
    if cm is None:
        return {}
    assert isinstance(cm, ConfigMap)
    out = {}
    for key, env in (("http_proxy", "HTTP_PROXY"), ("https_proxy", "HTTPS_PROXY"),
                     ("no_proxy", "NO_PROXY")):
        if cm.data.get(key):
            out[env] = cm.data[key]
    return out


def inject_proxy_config(nb: Notebook, proxy_env: dict[str, str]) -> None:
    spec = nb.spec.template.spec
    for c in spec.containers:
        if c.name == AUTH_PROXY_CONTAINER:
            continue
        have = {e.name for e in c.env}
        for k, v in proxy_env.items():
            if k not in have:
                c.env.append(EnvVar(k, v))
        # Trusted CA bundle for TLS through the egress proxy (ref
        # InjectProxyConfig mounts the odh-trusted-ca-bundle ConfigMap).
        if not any(m.name == "trusted-ca" for m in c.volume_mounts):
            c.volume_mounts.append(VolumeMount(
                name="trusted-ca",
                mount_path="/etc/pki/tls/certs/ca-bundle.crt",
                sub_path="ca-bundle.crt", read_only=True,
            ))
    _upsert_volume(spec.volumes, Volume(name="trusted-ca",
                                        config_map=TRUSTED_CA_CONFIGMAP))


class GatewayNotebookController(Controller):
    """Second reconciler on the Notebook kind (the ODH pattern: two
    operators, one CR — ref odh notebook_controller.go:126-198)."""

    KIND = "Notebook"
    OWNS = ("ServiceAccount", "Service", "Secret", "Route", "NetworkPolicy")
    # The mirrored trusted-ca ConfigMap is namespace-shared (not owned by
    # any one notebook, so no owner ref / no GC); watching the kind keeps
    # delete→recreate working for it.
    WATCHES = ("ConfigMap",)

    def __init__(self, *, gateway_domain: str = "apps.example.com",
                 lock_wait_budget: float = LOCK_WAIT_BUDGET,
                 clock=None):
        self.gateway_domain = gateway_domain
        self.lock_wait_budget = lock_wait_budget
        self.clock = clock or time.monotonic
        # (ns, name) -> (uid, monotonic deadline) for pull-secret
        # visibility. The uid pins the deadline to one incarnation of the
        # notebook: delete+recreate may coalesce into a single reconcile
        # in the dedup workqueue, so the NotFound cleanup can be skipped
        # entirely — a uid mismatch must start a fresh wait.
        self._lock_deadlines: dict[tuple[str, str], tuple[str, float]] = {}

    def watch_fanout_namespace(self, obj):
        """The source trusted-CA bundle lives in the system namespace but
        is mirrored into every notebook namespace — its updates must
        re-enqueue notebooks cluster-wide. Everything else (mirrors,
        unrelated system ConfigMaps) stays namespace-scoped to avoid
        O(all-notebooks) fan-out per event."""
        ns = obj.metadata.namespace or None
        if (ns == SYSTEM_NAMESPACE
                and obj.metadata.name == TRUSTED_CA_CONFIGMAP):
            return None
        return ns

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        try:
            nb = store.get("Notebook", namespace, name)
        except NotFound:
            # Drop any pending lock-wait deadline: a recreated same-name
            # notebook must start a fresh pull-secret wait, not inherit
            # an expired one and unlock immediately.
            self._lock_deadlines.pop((namespace, name), None)
            return Result()
        assert isinstance(nb, Notebook)

        self._reconcile_trusted_ca(store, nb)
        self._reconcile_network_policies(store, nb)
        if auth_enabled(nb):
            self._reconcile_service_account(store, nb)
            self._reconcile_tls_service(store, nb)
            self._reconcile_auth_secret(store, nb)
            self._reconcile_route(store, nb, target=AUTH_PROXY_PORT_NAME,
                                  tls="reencrypt")
        else:
            self._reconcile_route(store, nb, target="http", tls="edge")

        if locked(nb):
            return self._remove_lock(store, nb)
        return Result()

    # -- children ----------------------------------------------------------

    def _reconcile_trusted_ca(self, store: Store, nb: Notebook) -> None:
        """Mirror the system CA bundle into the notebook namespace (ref
        createProxyConfigMap, odh notebook_controller.go:200-260)."""
        src = store.try_get("ConfigMap", SYSTEM_NAMESPACE, TRUSTED_CA_CONFIGMAP)
        if src is None:
            return
        assert isinstance(src, ConfigMap)
        cm = ConfigMap(data=dict(src.data))
        cm.metadata.name = TRUSTED_CA_CONFIGMAP
        cm.metadata.namespace = nb.metadata.namespace
        existing = store.try_get("ConfigMap", cm.metadata.namespace, cm.metadata.name)
        if existing is None:
            store.create(cm)
        elif existing.data != cm.data:
            existing.data = cm.data
            store.update(existing)

    def _reconcile_network_policies(self, store: Store, nb: Notebook) -> None:
        """Ingress rules (ref notebook_network.go:130-208): the notebook
        port only from the gateway namespace; the auth port from anywhere
        (the proxy is the auth boundary)."""
        name, ns = nb.metadata.name, nb.metadata.namespace
        np = NetworkPolicy(
            allow_from_namespaces=[SYSTEM_NAMESPACE],
            allow_ports=[DEFAULT_PORT],
        )
        np.metadata.name = f"{name}-ctrl-np"
        np.metadata.namespace = ns
        reconcile_child(store, nb, np, _copy_netpol)
        if auth_enabled(nb):
            np2 = NetworkPolicy(allow_ports=[AUTH_PROXY_PORT])
            np2.metadata.name = f"{name}-auth-np"
            np2.metadata.namespace = ns
            reconcile_child(store, nb, np2, _copy_netpol)

    def _reconcile_service_account(self, store: Store, nb: Notebook) -> None:
        sa = ServiceAccount()
        sa.metadata.name = nb.metadata.name
        sa.metadata.namespace = nb.metadata.namespace
        # Route-redirect annotation (ref notebook_oauth.go:46-62).
        sa.metadata.annotations = {
            "kubeflow-tpu.dev/redirect-route": nb.metadata.name,
        }
        set_controller_reference(nb, sa)
        if store.try_get("ServiceAccount", sa.metadata.namespace,
                         sa.metadata.name) is None:
            store.create(sa)

    def _reconcile_tls_service(self, store: Store, nb: Notebook) -> None:
        name, ns = nb.metadata.name, nb.metadata.namespace
        svc = Service(spec=ServiceSpec(
            selector={"notebook-name": name},
            ports=[ServicePort(AUTH_PROXY_PORT_NAME, AUTH_SERVICE_PORT,
                               AUTH_PROXY_PORT)],
        ))
        svc.metadata.name = f"{name}-tls"
        svc.metadata.namespace = ns
        reconcile_child(store, nb, svc, copy_spec_and_labels)

    def _reconcile_auth_secret(self, store: Store, nb: Notebook) -> None:
        """Cookie secret, generated once (ref NewNotebookOAuthSecret
        notebook_oauth.go:187-209 — random 32B seed)."""
        name, ns = nb.metadata.name, nb.metadata.namespace
        if store.try_get("Secret", ns, f"{name}-auth-config") is not None:
            return
        sec = Secret(data={"cookie_secret": pysecrets.token_urlsafe(32)})
        sec.metadata.name = f"{name}-auth-config"
        sec.metadata.namespace = ns
        set_controller_reference(nb, sec)
        store.create(sec)

    def _reconcile_route(self, store: Store, nb: Notebook, *, target: str,
                         tls: str) -> None:
        name, ns = nb.metadata.name, nb.metadata.namespace
        route = Route(
            host=f"{name}-{ns}.{self.gateway_domain}",
            to_service=f"{name}-tls" if target == AUTH_PROXY_PORT_NAME else name,
            target_port=target,
            tls_termination=tls,
        )
        route.metadata.name = name
        route.metadata.namespace = ns
        reconcile_child(store, nb, route, _copy_route)

    # -- lock removal (ref RemoveReconciliationLock :94-122) ---------------

    def _remove_lock(self, store: Store, nb: Notebook) -> Result:
        """Unlock once the pull secret is visible on the ServiceAccount;
        after a wall-clock budget, unlock anyway (the reference swallows
        the wait error and removes the lock). The budget lives in
        controller memory, not an annotation: writing a retry counter to
        the CR would emit a MODIFIED event that re-enqueues immediately
        and defeats the backoff."""
        key = (nb.metadata.namespace, nb.metadata.name)
        sa_name = (nb.metadata.name if auth_enabled(nb)
                   else nb.spec.template.spec.service_account)
        ready = True
        if sa_name:
            sa = store.try_get("ServiceAccount", nb.metadata.namespace, sa_name)
            ready = sa is not None and bool(sa.image_pull_secrets)
        fresh = store.try_get("Notebook", nb.metadata.namespace,
                              nb.metadata.name)
        if fresh is None or not locked(fresh):
            self._lock_deadlines.pop(key, None)
            return Result()
        assert isinstance(fresh, Notebook)
        if not ready:
            now = self.clock()
            uid = fresh.metadata.uid
            entry = self._lock_deadlines.get(key)
            if entry is None or entry[0] != uid:
                entry = (uid, now + self.lock_wait_budget)
                self._lock_deadlines[key] = entry
            deadline = entry[1]
            if now < deadline:
                return Result(requeue_after=min(1.0, deadline - now))
        del fresh.metadata.annotations[STOP_ANNOTATION]
        store.update(fresh)
        self._lock_deadlines.pop(key, None)
        return Result()


class ServiceAccountPullSecretWebhook:
    """Models the platform's async pull-secret provisioning (on OpenShift a
    dockercfg secret appears on every new ServiceAccount; the lock-removal
    wait above is what makes that asynchrony safe)."""

    def __init__(self, store: Store):
        self.store = store

    def __call__(self, obj: Resource) -> None:
        if isinstance(obj, ServiceAccount) and not obj.image_pull_secrets:
            obj.image_pull_secrets.append(
                f"{obj.metadata.name}-dockercfg"
            )


def _copy_netpol(desired: NetworkPolicy, current: NetworkPolicy) -> bool:
    changed = (
        current.allow_from_namespaces != desired.allow_from_namespaces
        or current.allow_ports != desired.allow_ports
    )
    if changed:
        current.allow_from_namespaces = list(desired.allow_from_namespaces)
        current.allow_ports = list(desired.allow_ports)
    return changed


def _copy_route(desired: Route, current: Route) -> bool:
    # Host is platform-assigned once set; compare/copy everything else
    # (ref CompareNotebookRoutes blanks Host, notebook_route.go:65-73).
    changed = (
        current.to_service != desired.to_service
        or current.target_port != desired.target_port
        or current.tls_termination != desired.tls_termination
    )
    if changed:
        current.to_service = desired.to_service
        current.target_port = desired.target_port
        current.tls_termination = desired.tls_termination
    if not current.host:
        current.host = desired.host
        changed = True
    return changed
