"""Controller runtime: workqueues + reconcile loops (controller-runtime
equivalent).

Semantics preserved from the reference's runtime because every controller
depends on them (SURVEY.md §5 "race detection"):
- one in-flight reconcile per key (dedup workqueue) — the concurrency
  model that makes reconcilers race-free;
- watch-driven enqueue with owner mapping (a change to an owned object
  enqueues its owner, the `Owns()` pattern of SetupWithManager,
  notebook_controller.go:726-774);
- rate-limited retries on error and `Result(requeue_after=...)` for
  periodic resync (culling requeue, notebook_controller.go:279-281).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable

from kubeflow_tpu import obs
from kubeflow_tpu.api.core import Resource
from kubeflow_tpu.controlplane.store import (
    Conflict,
    OwnerGone,
    Store,
    WatchEvent,
)

log = logging.getLogger(__name__)

Key = tuple[str, str]  # (namespace, name)


@dataclasses.dataclass
class Result:
    requeue_after: float | None = None


class Controller:
    """Subclass and implement reconcile(store, namespace, name) -> Result."""

    KIND: str = ""                 # primary kind
    OWNS: tuple[str, ...] = ()     # owned kinds: events map back to owner
    WATCHES: tuple[str, ...] = ()  # extra kinds: enqueue primaries in scope

    def reconcile(self, store: Store, namespace: str, name: str) -> Result:
        raise NotImplementedError

    def watch_fanout_namespace(self, obj: Resource) -> str | None:
        """Which namespace's primaries a WATCHES event re-enqueues.
        Default: the event object's own namespace (keeps fan-out O(ns),
        not O(cluster)). Return None for a cluster-wide fan-out — e.g. a
        system-namespace source object mirrored into every namespace."""
        return obj.metadata.namespace or None

    def watch_keys(self, obj: Resource) -> list[Key] | None:
        """Precise routing for a WATCHES event: return the exact
        primary keys it concerns (possibly empty), or None for the
        namespace-wide fan-out. The k8s handler-mapping pattern — a
        controller that can name the affected primaries must, or every
        event costs an O(namespace) list + enqueue (quadratic under
        event storms)."""
        return None


class _WorkQueue:
    """Dedup queue with per-key delayed re-adds (rate-limited retries)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._ready: list[Key] = []
        self._pending: set[Key] = set()
        self._delayed: dict[Key, float] = {}
        self._failures: dict[Key, int] = {}
        self._added_at: dict[Key, float] = {}
        self._shutdown = False
        # queue-latency hook (seconds a key sat ready before a worker
        # took it); Manager wires it to the workqueue histogram.
        self.on_latency = None

    def add(self, key: Key) -> None:
        with self._cond:
            if key not in self._pending:
                self._pending.add(key)
                self._ready.append(key)
                self._added_at[key] = time.monotonic()
            self._cond.notify()

    def depth(self) -> int:
        """Keys waiting (ready + scheduled), the backlog gauge."""
        with self._cond:
            return len(self._ready) + len(self._delayed)

    def add_after(self, key: Key, delay: float) -> None:
        with self._cond:
            due = time.monotonic() + delay
            cur = self._delayed.get(key)
            if cur is None or due < cur:
                self._delayed[key] = due
            self._cond.notify()

    def add_rate_limited(self, key: Key) -> None:
        with self._cond:
            n = self._failures.get(key, 0)
            self._failures[key] = n + 1
        self.add_after(key, min(0.005 * (2**n), 8.0))

    def forget(self, key: Key) -> None:
        with self._cond:
            self._failures.pop(key, None)

    def get(self, timeout: float = 0.2) -> Key | None:
        with self._cond:
            deadline = time.monotonic() + timeout
            while True:
                now = time.monotonic()
                for key, due in list(self._delayed.items()):
                    if due <= now:
                        del self._delayed[key]
                        if key not in self._pending:
                            self._pending.add(key)
                            self._ready.append(key)
                            # latency clock starts when the key becomes
                            # READY — a deliberate requeue_after delay
                            # is scheduling, not queueing backlog
                            self._added_at[key] = now
                if self._ready:
                    key = self._ready.pop(0)
                    self._pending.discard(key)
                    added = self._added_at.pop(key, None)
                    if added is not None and self.on_latency is not None:
                        self.on_latency(time.monotonic() - added)
                    return key
                if self._shutdown or now >= deadline:
                    return None
                wait = deadline - now
                if self._delayed:
                    wait = min(wait, max(0.0, min(self._delayed.values()) - now))
                self._cond.wait(wait if wait > 0 else 0.001)

    def shutdown(self):
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()


class Manager:
    """Runs controllers against a store. start()/stop(), or use
    wait_idle() in tests for deterministic settling (envtest-style)."""

    def __init__(self, store: Store, metrics=None, tracer=None):
        self.store = store
        self.metrics = metrics   # ControlPlaneMetrics | None
        self.tracer = tracer or obs.DEFAULT_TRACER
        self._controllers: list[tuple[Controller, _WorkQueue]] = []
        self._threads: list[threading.Thread] = []
        self._watch = None
        self._stop = threading.Event()
        self._active = 0
        self._active_cond = threading.Condition()
        # Scrape-time depth gauge: one collector covers every queue,
        # registered once (controllers added later are picked up — the
        # collector walks the live list).
        registry = getattr(metrics, "registry", None)
        if registry is not None and hasattr(metrics, "workqueue_depth"):
            registry.register_collector(self._scrape_queue_depth)

    def _scrape_queue_depth(self) -> None:
        for ctrl, wq in list(self._controllers):
            self.metrics.workqueue_depth.set(
                float(wq.depth()), kind=type(ctrl).__name__)

    def register(self, controller: Controller) -> None:
        wq = _WorkQueue()
        if self.metrics is not None and hasattr(self.metrics,
                                                "record_queue_latency"):
            kind = type(controller).__name__
            wq.on_latency = (
                lambda s, _k=kind: self.metrics.record_queue_latency(_k, s))
        self._controllers.append((controller, wq))

    def enqueue_all(self, kind: str, namespace: str | None = None) -> None:
        """Re-enqueue every primary of `kind` (the reference's fsnotify
        full-re-reconcile on config change, profile_controller.go:356-405)."""
        for ctrl, wq in self._controllers:
            if ctrl.KIND != kind:
                continue
            for obj in self.store.list(kind, namespace):
                wq.add((obj.metadata.namespace, obj.metadata.name))

    def start(self) -> None:
        self._watch = self.store.watch()
        t = threading.Thread(target=self._dispatch_loop, daemon=True,
                             name="mgr-dispatch")
        t.start()
        self._threads.append(t)
        for ctrl, wq in self._controllers:
            # Kick initial reconcile for pre-existing primaries.
            for obj in self.store.list(ctrl.KIND):
                wq.add((obj.metadata.namespace, obj.metadata.name))
            t = threading.Thread(
                target=self._worker_loop, args=(ctrl, wq), daemon=True,
                name=f"ctrl-{ctrl.KIND}",
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self._watch is not None:
            self._watch.close()
        for _, wq in self._controllers:
            wq.shutdown()
        for t in self._threads:
            t.join(timeout=5)

    # -- event routing -----------------------------------------------------

    def _dispatch_loop(self) -> None:
        for event in self._watch:
            if self._stop.is_set():
                return
            self._dispatch(event)

    def _dispatch(self, event: WatchEvent) -> None:
        obj = event.resource
        for ctrl, wq in self._controllers:
            if obj.kind == ctrl.KIND:
                wq.add((obj.metadata.namespace, obj.metadata.name))
            elif obj.kind in ctrl.OWNS:
                for ref in obj.metadata.owner_references:
                    if ref.kind == ctrl.KIND:
                        wq.add((obj.metadata.namespace, ref.name))
            elif obj.kind in ctrl.WATCHES:
                keys = ctrl.watch_keys(obj)
                if keys is not None:
                    for key in keys:
                        wq.add(key)
                    continue
                ns = ctrl.watch_fanout_namespace(obj)
                for primary in self.store.list(ctrl.KIND, ns):
                    wq.add((primary.metadata.namespace, primary.metadata.name))

    # -- workers -----------------------------------------------------------

    def _worker_loop(self, ctrl: Controller, wq: _WorkQueue) -> None:
        while not self._stop.is_set():
            key = wq.get(timeout=0.2)
            if key is None:
                continue
            with self._active_cond:
                self._active += 1
            t0 = time.perf_counter()
            try:
                with self.tracer.span("reconcile",
                                      kind=type(ctrl).__name__,
                                      namespace=key[0], name=key[1]):
                    result = ctrl.reconcile(self.store, key[0], key[1])
            except Conflict:
                # A conflict retry is neither success nor failure, but a
                # sustained storm must be visible on reconcile_total.
                if self.metrics is not None:
                    self.metrics.record_reconcile(
                        type(ctrl).__name__, False, severity="conflict")
                wq.add_rate_limited(key)
            except OwnerGone:
                # The primary was deleted while this reconcile was in
                # flight and the store refused to resurrect its child.
                # Not an error: the DELETE's own watch event re-enqueues
                # the key, and that reconcile sees NotFound and no-ops.
                log.debug("reconcile %s %s: owner gone mid-flight",
                          ctrl.KIND, key)
                if self.metrics is not None:
                    self.metrics.record_reconcile(type(ctrl).__name__, True)
                wq.forget(key)
            except Exception:
                log.exception("reconcile %s %s failed", ctrl.KIND, key)
                # ref monitoring.go:74 IncRequestErrorCounter (severity label)
                if self.metrics is not None:
                    self.metrics.record_reconcile(type(ctrl).__name__, False)
                wq.add_rate_limited(key)
            else:
                if self.metrics is not None:
                    self.metrics.record_reconcile(type(ctrl).__name__, True)
                wq.forget(key)
                if result and result.requeue_after:
                    wq.add_after(key, result.requeue_after)
            finally:
                # Duration on every outcome (success, conflict, crash):
                # a controller that only fails slowly must still show up
                # in the latency histogram.
                if self.metrics is not None and hasattr(
                        self.metrics, "record_reconcile_duration"):
                    self.metrics.record_reconcile_duration(
                        type(ctrl).__name__, time.perf_counter() - t0)
                with self._active_cond:
                    self._active -= 1
                    self._active_cond.notify_all()

    # -- test support ------------------------------------------------------

    def wait_idle(self, timeout: float = 5.0, settle: float = 0.05) -> bool:
        """Wait until all queues are empty and workers idle for `settle`s.
        Delayed requeues (periodic resync) are ignored."""
        deadline = time.monotonic() + timeout
        idle_since = None
        while time.monotonic() < deadline:
            busy = self._active > 0 or any(
                wq._ready or wq._pending for _, wq in self._controllers
            )
            if busy:
                idle_since = None
            elif idle_since is None:
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since >= settle:
                return True
            time.sleep(0.01)
        return False
