"""Versioned object store with watches — the apiserver equivalent.

Replaces the reference's L0 (k8s API server + etcd) for hermetic,
in-process operation, in the same spirit the reference's envtest boots a
real apiserver for integration tests (SURVEY.md §4 tier 2). Semantics
kept from that world because the controllers rely on them:

- optimistic concurrency: update must carry the current resource_version
  or it raises Conflict (the reference wraps updates in
  retry.RetryOnConflict, e.g. notebook_route.go:119-131);
- finalizers: delete marks deletion_timestamp and the object lingers
  until controllers strip their finalizers (profile_controller.go:284-319);
- owner references: deleting an owner cascades to owned objects
  (SetControllerReference semantics);
- watches: every mutation fans out a WatchEvent to subscribers — the
  controller runtime's trigger;
- admission chain: mutating webhooks run on create (and optionally
  update), exactly where the reference's admission chain sits (L3).

The store is intentionally synchronous + threadsafe. A native C++
backend implementing the same contract can be slotted in via
`kubeflow_tpu.native` (the reference has no native runtime; ours is the
TPU-era equivalent of its Go controller binaries).
"""

from __future__ import annotations

import fnmatch
import itertools
import queue
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Iterable

from kubeflow_tpu.api.core import Event, Resource


class StoreError(Exception):
    pass


class NotFound(StoreError):
    pass


class AlreadyExists(StoreError):
    pass


class Conflict(StoreError):
    pass


class AdmissionDenied(StoreError):
    pass


class OwnerGone(StoreError):
    """Create rejected: the controller owner-ref uid no longer exists.

    k8s lets such creates through and its GC collects the orphan later;
    this store has no async GC, so admitting the object would resurrect
    a cascade-deleted child forever (the round-3 Experiment→Trial race:
    reconcile read the Experiment, DELETE cascaded the Trials, then the
    in-flight reconcile re-created them with the dead owner's uid).
    Rejecting at create is the synchronous equivalent of that GC.
    """


@dataclass(frozen=True)
class WatchEvent:
    type: str            # ADDED | MODIFIED | DELETED
    resource: Resource


Mutator = Callable[[Resource], None]     # in-place mutate or raise AdmissionDenied


class Store:
    # Event GC bounds (k8s inherits a 1h event TTL from etcd leases;
    # the per-object cap bounds hot reconcile loops that emit faster
    # than the TTL drains — round-1/2 left growth unbounded).
    EVENT_TTL_SECS = 3600.0
    EVENTS_PER_OBJECT = 25

    def __init__(self, *, event_ttl: float | None = None,
                 events_per_object: int | None = None):
        self._lock = threading.RLock()
        self._objects: dict[tuple[str, str, str], Resource] = {}
        self._rv = itertools.count(1)
        self._watchers: list[tuple[queue.Queue, tuple[str, ...] | None]] = []
        # kind -> mutators run at create; "*" applies to every kind
        self._mutating_webhooks: dict[str, list[Mutator]] = {}
        self.event_ttl = (self.EVENT_TTL_SECS if event_ttl is None
                          else event_ttl)
        self.events_per_object = (self.EVENTS_PER_OBJECT
                                  if events_per_object is None
                                  else events_per_object)
        # namespace -> Event keys: emit/GC touch only a namespace's
        # events instead of scanning the whole object map under the
        # global lock (the apiserver-equivalent's hot path).
        self._events_by_ns: dict[str, set[tuple[str, str, str]]] = {}
        # uid -> key: O(1) liveness checks for owner references.
        self._uids: dict[str, tuple[str, str, str]] = {}
        # kind -> {key -> Resource}: list(kind) must not scan every
        # object in the cluster (an informer-style index; the
        # reconcile-fanout loadtest is the regression harness).
        self._by_kind: dict[str, dict[tuple[str, str, str], Resource]] = {}
        # (kind, label, value) -> keys: exact-match label selectors
        # (every controller's owned-object lookup, e.g. Pods by
        # notebook-name) resolve without scanning the kind. Maintained
        # for every label on every object — label sets are tiny.
        self._labels: dict[tuple[str, str, str],
                           set[tuple[str, str, str]]] = {}
        # owner uid -> owned keys: the informer ownerRef index. Gang
        # controllers resolve "my pods" in O(gang), not O(namespace) —
        # the other half of the reconcile-fanout quadratic.
        self._by_owner: dict[str, set[tuple[str, str, str]]] = {}

    # -- admission ---------------------------------------------------------

    def register_mutating_webhook(self, kind: str, fn: Mutator) -> None:
        self._mutating_webhooks.setdefault(kind, []).append(fn)

    def _admit(self, obj: Resource) -> None:
        for fn in self._mutating_webhooks.get("*", []):
            fn(obj)
        for fn in self._mutating_webhooks.get(obj.kind, []):
            fn(obj)

    # -- CRUD --------------------------------------------------------------

    def create(self, obj: Resource, *, dry_run: bool = False) -> Resource:
        with self._lock:
            if obj.key in self._objects:
                raise AlreadyExists(f"{obj.key} exists")
            obj = obj.clone()
            self._admit(obj)
            for ref in obj.metadata.owner_references:
                if ref.controller and ref.uid and ref.uid not in self._uids:
                    raise OwnerGone(
                        f"{obj.key}: controller owner {ref.kind}/{ref.name} "
                        f"uid={ref.uid} no longer exists"
                    )
            if dry_run:
                return obj
            m = obj.metadata
            m.uid = m.uid or uuid.uuid4().hex
            m.resource_version = next(self._rv)
            m.generation = 1
            m.creation_timestamp = m.creation_timestamp or time.time()
            self._objects[obj.key] = obj
            self._by_kind.setdefault(obj.kind, {})[obj.key] = obj
            self._index_labels(obj)
            self._uids[m.uid] = obj.key
            if obj.kind == "Event":
                self._events_by_ns.setdefault(
                    m.namespace, set()).add(obj.key)
            self._notify(WatchEvent("ADDED", obj.clone()))
            return obj.clone()

    def get(self, kind: str, namespace: str, name: str) -> Resource:
        with self._lock:
            obj = self._objects.get((kind, namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            return obj.clone()

    def try_get(self, kind: str, namespace: str, name: str) -> Resource | None:
        try:
            return self.get(kind, namespace, name)
        except NotFound:
            return None

    def update(self, obj: Resource) -> Resource:
        with self._lock:
            cur = self._objects.get(obj.key)
            if cur is None:
                raise NotFound(f"{obj.key}")
            if obj.metadata.resource_version != cur.metadata.resource_version:
                raise Conflict(
                    f"{obj.key}: rv {obj.metadata.resource_version} != "
                    f"{cur.metadata.resource_version}"
                )
            obj = obj.clone()
            m = obj.metadata
            m.uid = cur.metadata.uid
            m.creation_timestamp = cur.metadata.creation_timestamp
            m.resource_version = next(self._rv)
            m.generation = cur.metadata.generation + 1
            self._unindex_labels(cur)
            self._objects[obj.key] = obj
            self._by_kind.setdefault(obj.kind, {})[obj.key] = obj
            self._index_labels(obj)
            self._notify(WatchEvent("MODIFIED", obj.clone()))
            # A finalizer strip on a deleting object may complete deletion.
            if m.deletion_timestamp is not None and not m.finalizers:
                self._finalize_delete(obj.key)
            return obj.clone()

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            key = (kind, namespace, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            if cur.metadata.finalizers:
                if cur.metadata.deletion_timestamp is None:
                    cur.metadata.deletion_timestamp = time.time()
                    cur.metadata.resource_version = next(self._rv)
                    self._notify(WatchEvent("MODIFIED", cur.clone()))
                return
            self._finalize_delete(key)

    def _finalize_delete(self, key) -> None:
        obj = self._objects.pop(key, None)
        if obj is None:
            return
        self._by_kind.get(obj.kind, {}).pop(key, None)
        self._unindex_labels(obj)
        self._uids.pop(obj.metadata.uid, None)
        if obj.kind == "Event":
            self._events_by_ns.get(obj.metadata.namespace, set()).discard(key)
        self._notify(WatchEvent("DELETED", obj.clone()))
        # Cascade: delete objects owned by this one — resolved through
        # the owner index (O(owned)), not a cluster scan; the delete
        # path must scale like the reconcile path it serves.
        owned = list(self._by_owner.get(obj.metadata.uid, ()))
        # Deleting a Namespace deletes everything namespaced inside it
        # (rare admin operation: the scan is acceptable here).
        if obj.kind == "Namespace":
            owned += [
                o.key
                for o in list(self._objects.values())
                if o.metadata.namespace == obj.metadata.name
            ]
        for k, ns, n in owned:
            try:
                self.delete(k, ns, n)
            except NotFound:
                pass

    def _index_labels(self, obj: Resource) -> None:
        for k, v in obj.metadata.labels.items():
            self._labels.setdefault((obj.kind, k, v), set()).add(obj.key)
        for ref in obj.metadata.owner_references:
            if ref.uid:
                self._by_owner.setdefault(ref.uid, set()).add(obj.key)

    def _unindex_labels(self, obj: Resource) -> None:
        for k, v in obj.metadata.labels.items():
            entry = self._labels.get((obj.kind, k, v))
            if entry is not None:
                entry.discard(obj.key)
                if not entry:
                    del self._labels[(obj.kind, k, v)]
        for ref in obj.metadata.owner_references:
            entry = self._by_owner.get(ref.uid)
            if entry is not None:
                entry.discard(obj.key)
                if not entry:
                    del self._by_owner[ref.uid]

    # -- queries -----------------------------------------------------------

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        *,
        label_selector: dict[str, str] | None = None,
        field_match: Callable[[Resource], bool] | None = None,
        owner_uid: str | None = None,
    ) -> list[Resource]:
        with self._lock:
            pool = self._by_kind.get(kind, {})
            candidates = pool.values()
            if owner_uid is not None:
                candidates = [
                    pool[key]
                    for key in self._by_owner.get(owner_uid, ())
                    if key in pool
                ]
            elif label_selector:
                # Narrow via the label index when any selector entry is
                # an exact value (wildcards still scan): pick the
                # smallest posting set, verify the full selector below.
                exact = [
                    self._labels.get((kind, k, v), set())
                    for k, v in label_selector.items()
                    if not any(c in v for c in "*?[")
                ]
                if exact:
                    keys = min(exact, key=len)
                    candidates = [pool[key] for key in keys
                                  if key in pool]
            out = []
            for obj in candidates:
                if (namespace is not None
                        and obj.metadata.namespace != namespace):
                    continue
                if label_selector and not _labels_match(
                    obj.metadata.labels, label_selector
                ):
                    continue
                if field_match and not field_match(obj):
                    continue
                out.append(obj.clone())
            return sorted(out, key=lambda o: (o.metadata.namespace, o.metadata.name))

    # -- events ------------------------------------------------------------

    def emit_event(
        self, involved: Resource, type_: str, reason: str, message: str
    ) -> None:
        ns = involved.metadata.namespace or "default"
        now = time.time()
        with self._lock:
            # Duplicate aggregation: a repeat of an existing live event
            # bumps count/last_timestamp in place (k8s event count
            # semantics) — reconcile loops that re-emit the same warning
            # every pass cost one object, not one per pass. The
            # namespace index keeps this off the full object map.
            hit = None
            for key in self._events_by_ns.get(ns, ()):
                obj = self._objects.get(key)
                if obj is None:
                    continue
                if (obj.involved_kind == involved.kind
                        and obj.involved_name == involved.metadata.name
                        and obj.type == type_ and obj.reason == reason
                        and obj.message == message
                        and now - obj.timestamp < self.event_ttl):
                    hit = obj
                    break
            if hit is not None:
                hit.count += 1
                hit.last_timestamp = now
                hit.metadata.resource_version = next(self._rv)
                self._notify(WatchEvent("MODIFIED", hit.clone()))
                self._gc_events(ns, involved)
                return
        ev = Event(
            involved_kind=involved.kind,
            involved_name=involved.metadata.name,
            type=type_,
            reason=reason,
            message=message,
            last_timestamp=now,
        )
        ev.metadata.namespace = ns
        ev.metadata.name = f"{involved.metadata.name}.{uuid.uuid4().hex[:8]}"
        self.create(ev)
        self._gc_events(ns, involved)

    def _gc_events(self, namespace: str, involved: Resource) -> None:
        """Bound event growth: drop expired events namespace-wide and
        keep only the newest `events_per_object` for the emitting
        object. Runs on the emit path only — reads (events_for) stay
        scan-only."""
        now = time.time()
        with self._lock:
            expired: set[tuple[str, str, str]] = set()
            mine: list[tuple[float, tuple[str, str, str]]] = []
            for key in self._events_by_ns.get(namespace, ()):
                obj = self._objects.get(key)
                if obj is None:
                    continue
                fresh_at = max(obj.timestamp, obj.last_timestamp)
                if now - fresh_at >= self.event_ttl:
                    expired.add(key)
                elif (obj.involved_kind == involved.kind
                      and obj.involved_name == involved.metadata.name):
                    mine.append((fresh_at, key))
            mine.sort(reverse=True)
            overflow = [key for _, key in mine[self.events_per_object:]]
            # Events own nothing and carry no finalizers, so the full
            # delete bookkeeping applies directly — ONE place maintains
            # the store's indexes (a hand-mirrored copy here silently
            # corrupted index additions twice during round 4).
            for key in list(expired) + overflow:
                self._finalize_delete(key)

    def events_for(self, kind: str, namespace: str, name: str) -> list[Event]:
        return [
            e
            for e in self.list("Event", namespace)
            if e.involved_kind == kind and e.involved_name == name
        ]

    # -- watches -----------------------------------------------------------

    def watch(self, kinds: Iterable[str] | None = None) -> "Watch":
        q: queue.Queue = queue.Queue()
        kt = tuple(kinds) if kinds is not None else None
        with self._lock:
            self._watchers.append((q, kt))
        return Watch(self, q)

    def _unwatch(self, q: queue.Queue) -> None:
        with self._lock:
            self._watchers = [(w, k) for (w, k) in self._watchers if w is not q]

    def _notify(self, event: WatchEvent) -> None:
        for q, kinds in self._watchers:
            if kinds is None or event.resource.kind in kinds:
                q.put(event)


class Watch:
    """Iterator over store events; close() to stop."""

    _SENTINEL = object()

    def __init__(self, store: Store, q: queue.Queue):
        self._store = store
        self._q = q
        self._closed = False

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._SENTINEL:
                return
            yield item

    def get(self, timeout: float | None = None) -> WatchEvent | None:
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is self._SENTINEL:
            return None
        return item

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._store._unwatch(self._q)
            self._q.put(self._SENTINEL)


def _labels_match(labels: dict[str, str], selector: dict[str, str]) -> bool:
    for k, want in selector.items():
        have = labels.get(k)
        if have is None:
            return False
        if want not in ("*", have) and not fnmatch.fnmatch(have, want):
            return False
    return True


def set_controller_reference(owner: Resource, owned: Resource) -> None:
    """SetControllerReference equivalent (ref reconcilehelper usage)."""
    from kubeflow_tpu.api.core import OwnerReference

    owned.metadata.owner_references = [
        OwnerReference(kind=owner.kind, name=owner.metadata.name,
                       uid=owner.metadata.uid, controller=True)
    ]
