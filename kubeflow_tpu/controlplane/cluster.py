"""Cluster assembly: store + webhook + controllers under one Manager.

The equivalent of the reference's per-controller main.go wiring
(notebook-controller/main.go, profile-controller/main.go) plus the
envtest environment used by its integration suites — one call builds a
fully-working in-process control plane.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeflow_tpu.controlplane.controllers.culler import ActivityProbe, Culler
from kubeflow_tpu.controlplane.controllers.hpo import (
    ExperimentController,
    StepwiseTrialExecutor,
    TrialController,
    TrialExecutor,
)
from kubeflow_tpu.controlplane.controllers.gateway import (
    GatewayNotebookController,
    NotebookGatewayWebhook,
    ServiceAccountPullSecretWebhook,
)
from kubeflow_tpu.controlplane.controllers.notebook import NotebookController
from kubeflow_tpu.controlplane.controllers.profile import (
    ProfileController,
    WorkloadIdentityPlugin,
)
from kubeflow_tpu.controlplane.controllers.modelserver import (
    ModelServerController,
)
from kubeflow_tpu.controlplane.controllers.tensorboard import TensorboardController
from kubeflow_tpu.controlplane.controllers.workload import (
    DeploymentController,
    NodePool,
    Scheduler,
    StatefulSetController,
)
from kubeflow_tpu.controlplane.metrics import ControlPlaneMetrics
from kubeflow_tpu.controlplane.runtime import Manager
from kubeflow_tpu.controlplane.store import Store
from kubeflow_tpu.controlplane.webhook import PodDefaultWebhook


@dataclass
class ClusterConfig:
    tpu_slices: dict[str, int] = field(default_factory=dict)
    use_routing: bool = True
    enable_culling: bool = False
    cull_idle_time: float = 1440 * 60.0
    cull_check_period: float = 60.0
    activity_probe: ActivityProbe | None = None
    default_namespace_labels: dict[str, str] = field(default_factory=dict)
    enable_workload_identity: bool = False
    cluster_admins: set[str] = field(default_factory=set)
    # Gateway layer (the odh-notebook-controller equivalent): auth-proxy
    # sidecar injection, Routes, NetworkPolicies, reconciliation lock.
    enable_gateway: bool = False
    gateway_domain: str = "apps.example.com"
    # Hermetic HPO: when set, trial pods "run" this objective in-process
    # (the envtest-style fake kubelet for trials). None in production.
    trial_executor: TrialExecutor | None = None
    # Stepwise variant: (assignment, step) -> value | None(done); one
    # step per reconcile with durable intermediate reports — the path
    # the median stopping rule observes. Mutually exclusive with
    # trial_executor.
    stepwise_trial_executor: StepwiseTrialExecutor | None = None
    # Hot-watched default-namespace-labels file (JSON/YAML mapping); a
    # change re-reconciles every Profile (the fsnotify mechanism,
    # ref profile_controller.go:356-405). Overrides
    # default_namespace_labels when set.
    namespace_labels_path: str | None = None


class Cluster:
    """In-process control plane. Use as a context manager or call
    start()/stop() explicitly."""

    def __init__(self, config: ClusterConfig | None = None):
        self.config = config or ClusterConfig()
        self.store = Store()
        self.scheduler = Scheduler(NodePool(dict(self.config.tpu_slices)))
        self.webhook = PodDefaultWebhook(self.store)
        self.store.register_mutating_webhook("Pod", self.webhook)
        self.metrics = ControlPlaneMetrics(self.store)
        # One tracer spans the whole control plane: reconcile spans and
        # the web layer's request spans land in the same ring, so the
        # dashboard's /debug/traces correlates them.
        from kubeflow_tpu.obs import Tracer

        self.tracer = Tracer()
        self.manager = Manager(self.store, metrics=self.metrics,
                               tracer=self.tracer)
        self.notebook_controller = NotebookController(
            use_routing=self.config.use_routing, metrics=self.metrics
        )
        self.statefulset_controller = StatefulSetController(self.scheduler)
        self.labels_config = None
        initial_labels = dict(self.config.default_namespace_labels)
        if self.config.namespace_labels_path:
            from kubeflow_tpu.utils.config import WatchedConfig

            self.labels_config = WatchedConfig(
                self.config.namespace_labels_path, default=initial_labels)
            initial_labels = dict(self.labels_config.data or {})
        self.profile_controller = ProfileController(
            default_namespace_labels=initial_labels,
            plugins=([WorkloadIdentityPlugin()]
                     if self.config.enable_workload_identity else []),
        )
        if self.labels_config is not None:
            def _labels_changed(data, _ctrl=self.profile_controller):
                _ctrl.default_namespace_labels = dict(data or {})
                self.manager.enqueue_all("Profile")

            self.labels_config.on_change(_labels_changed)
        self.tensorboard_controller = TensorboardController(
            use_routing=self.config.use_routing
        )
        self.modelserver_controller = ModelServerController(
            use_routing=self.config.use_routing
        )
        self.deployment_controller = DeploymentController()
        self.experiment_controller = ExperimentController()
        self.trial_controller = TrialController(
            executor=self.config.trial_executor,
            stepwise_executor=self.config.stepwise_trial_executor)
        self.manager.register(self.experiment_controller)
        self.manager.register(self.trial_controller)
        self.manager.register(self.notebook_controller)
        self.manager.register(self.statefulset_controller)
        self.manager.register(self.profile_controller)
        self.manager.register(self.tensorboard_controller)
        self.manager.register(self.modelserver_controller)
        self.manager.register(self.deployment_controller)
        self.gateway_controller = None
        self.gateway_webhook = None
        if self.config.enable_gateway:
            self.gateway_webhook = NotebookGatewayWebhook(self.store)
            self.store.register_mutating_webhook("Notebook", self.gateway_webhook)
            self.store.register_mutating_webhook(
                "ServiceAccount", ServiceAccountPullSecretWebhook(self.store)
            )
            self.gateway_controller = GatewayNotebookController(
                gateway_domain=self.config.gateway_domain
            )
            self.manager.register(self.gateway_controller)
        self.culler = None
        if self.config.enable_culling and self.config.activity_probe is not None:
            self.culler = Culler(
                self.config.activity_probe,
                idle_time=self.config.cull_idle_time,
                check_period=self.config.cull_check_period,
                metrics=self.metrics,
            )
            self.manager.register(self.culler)

    @property
    def cluster_admins(self) -> set[str]:
        return set(self.config.cluster_admins)

    def create_web_app(self, **kwargs):
        """The platform web app wired to this cluster (admins included) —
        use this instead of calling create_platform_app by hand so
        ClusterConfig.cluster_admins actually takes effect."""
        from kubeflow_tpu.web.platform import create_platform_app

        kwargs.setdefault("cluster_admins", self.cluster_admins)
        kwargs.setdefault("metrics", self.metrics)
        kwargs.setdefault("tracer", self.tracer)
        return create_platform_app(self.store, **kwargs)

    def start(self) -> "Cluster":
        if self.labels_config is not None:
            self.labels_config.start()
        self.manager.start()
        return self

    def stop(self) -> None:
        if self.labels_config is not None:
            self.labels_config.stop()
        self.manager.stop()

    def wait_idle(self, timeout: float = 5.0) -> bool:
        return self.manager.wait_idle(timeout=timeout)

    def __enter__(self) -> "Cluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
