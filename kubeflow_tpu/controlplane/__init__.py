"""Control plane: object store, reconciler runtime, controllers, webhook.

The TPU-native re-design of the reference's L0-L3 stack:
- store.py      — versioned object store + watch fanout (apiserver/envtest
                  equivalent; pluggable native C++ backend)
- runtime.py    — controller manager: workqueues, reconcile loops,
                  owner-based requeue (controller-runtime equivalent)
- webhook.py    — admission chain: TpuPodDefault merge + TPU env injection
- controllers/  — notebook, profile, tensorboard reconcilers + culler
"""

from kubeflow_tpu.controlplane.store import Store, WatchEvent, Conflict, NotFound
from kubeflow_tpu.controlplane.runtime import Controller, Manager, Result
