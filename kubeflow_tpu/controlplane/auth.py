"""AuthN/AuthZ for the web surface (reference L4).

- authn: trusted-header identity, the reference's model throughout
  (crud_backend/authn.py:12-67, settings.py:5-6 USERID_HEADER default
  `kubeflow-userid`; dashboard server.ts:25-32). No sessions: the mesh
  in front injects the header.
- authz: SubjectAccessReview-style checks resolved against RoleBindings
  in the store (crud_backend/authz.py:25-132 does a SAR per call; here
  the store IS the authority so the check is a direct lookup with the
  same verb model).
- csrf: double-submit cookie (crud_backend/csrf.py:57-111).
"""

from __future__ import annotations

import hmac
import secrets
from dataclasses import dataclass

from kubeflow_tpu.controlplane.store import Store

USERID_HEADER = "kubeflow-userid"
USERID_PREFIX = ""          # ref strips an optional prefix (authn.py)
CSRF_COOKIE = "XSRF-TOKEN"
CSRF_HEADER = "X-XSRF-TOKEN"

# Namespaces never claimable through self-serve profiles: owning
# kubeflow-tpu-system would mint cluster admins (is_cluster_admin reads
# admin RoleBindings from it).
RESERVED_NAMESPACES = frozenset({
    "kubeflow-tpu-system", "default", "kube-system", "kube-public",
})
RESERVED_PREFIXES = ("kube-", "kubeflow-tpu-")


def is_reserved_namespace(name: str) -> bool:
    return name in RESERVED_NAMESPACES or name.startswith(RESERVED_PREFIXES)


# verb sets per role (mirrors k8s edit/view ClusterRole semantics)
_ROLE_VERBS = {
    "kubeflow-tpu-admin": {"get", "list", "create", "update", "delete"},
    "kubeflow-tpu-edit": {"get", "list", "create", "update", "delete"},
    "kubeflow-tpu-view": {"get", "list"},
}


class Unauthenticated(Exception):
    status = 401


class Forbidden(Exception):
    status = 403


@dataclass(frozen=True)
class User:
    name: str


def authenticate(headers) -> User:
    """Extract identity from trusted headers (authn.py:12-67)."""
    raw = headers.get(USERID_HEADER, "")
    if not raw:
        raise Unauthenticated(f"missing {USERID_HEADER} header")
    if USERID_PREFIX and raw.startswith(USERID_PREFIX):
        raw = raw[len(USERID_PREFIX):]
    return User(raw)


def is_cluster_admin(store: Store, user: User,
                     cluster_admins: set[str] | None = None) -> bool:
    if cluster_admins and user.name in cluster_admins:
        return True
    for rb in store.list("RoleBinding", "kubeflow-tpu-system"):
        if rb.role == "kubeflow-tpu-admin" and user.name in rb.subjects:
            return True
    return False


def ensure_authorized(
    store: Store,
    user: User,
    verb: str,
    kind: str,
    namespace: str,
    *,
    cluster_admins: set[str] | None = None,
) -> None:
    """SAR-equivalent (authz.py:46-80): raise Forbidden unless allowed."""
    if is_cluster_admin(store, user, cluster_admins):
        return
    for rb in store.list("RoleBinding", namespace):
        if user.name not in rb.subjects:
            continue
        if verb in _ROLE_VERBS.get(rb.role, set()):
            return
    raise Forbidden(
        f"user {user.name!r} cannot {verb} {kind} in namespace {namespace!r}"
    )


def namespaces_for(store: Store, user: User,
                   cluster_admins: set[str] | None = None) -> list[str]:
    """Namespaces the user can at least view (dashboard env-info)."""
    if is_cluster_admin(store, user, cluster_admins):
        return sorted(
            n.metadata.name for n in store.list("Namespace")
        )
    out = set()
    for rb in store.list("RoleBinding", None):
        if user.name in rb.subjects and rb.metadata.namespace:
            out.add(rb.metadata.namespace)
    return sorted(out)


# -- CSRF (double-submit cookie, csrf.py:57-111) ----------------------------


def new_csrf_token() -> str:
    return secrets.token_urlsafe(32)


def check_csrf(cookie_token: str | None, header_token: str | None) -> bool:
    if not cookie_token or not header_token:
        return False
    return hmac.compare_digest(cookie_token, header_token)
