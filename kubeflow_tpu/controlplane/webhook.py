"""Admission webhook: TpuPodDefault merge engine + TPU env injection.

Re-design of the reference's PodDefault mutating webhook
(admission-webhook/main.go): on pod create, select the namespace's
TpuPodDefaults by label selector (ref filterPodDefaults main.go:70-95),
refuse to apply on conflict (ref safeToApplyPodDefaultsOnPod
main.go:99-133 — conflict-refusal is load-bearing, SURVEY.md §7 hard
part b), merge env/volumes/mounts/tolerations/labels/annotations/
command/args (ref merge fns main.go:153-364), and stamp an applied
annotation (ref main.go:424-426).

TPU-native addition (the whole point, SURVEY.md §2b "collective
communication backend"): pods belonging to a TPU gang get
TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / coordinator env derived from
their gang ordinal and the slice topology, so in-pod
`jax.distributed.initialize()` comes up over ICI with no NCCL/MPI
rendezvous. The reference's closest mechanism is env merging
(main.go:153-188); here topology env is computed, not configured.
"""

from __future__ import annotations

import logging

from kubeflow_tpu.api.core import EnvVar, Pod, Resource
from kubeflow_tpu.api.crds import (
    PODDEFAULT_APPLIED_PREFIX,
    WEBHOOK_EXCLUDE_ANNOTATION,
    TpuPodDefault,
)
from kubeflow_tpu.controlplane.store import AdmissionDenied, Store, _labels_match
from kubeflow_tpu.parallel.mesh import SLICE_TOPOLOGIES

log = logging.getLogger(__name__)

# Gang bookkeeping labels set by the notebook controller on pods it creates.
GANG_NAME_LABEL = "kubeflow-tpu.dev/gang-name"
GANG_ORDINAL_LABEL = "kubeflow-tpu.dev/gang-ordinal"
GANG_SIZE_LABEL = "kubeflow-tpu.dev/gang-size"
TOPOLOGY_LABEL = "kubeflow-tpu.dev/tpu-topology"
MESH_LABEL = "kubeflow-tpu.dev/mesh"
NUM_SLICES_LABEL = "kubeflow-tpu.dev/num-slices"

JAX_COORDINATOR_PORT = 8476
MEGASCALE_COORDINATOR_PORT = 8080
POD_START_TIME_ENV = "KFTPU_POD_START_TIME"


class PodDefaultWebhook:
    """Mutating webhook for Pods; register on the store's admission chain."""

    def __init__(self, store: Store):
        self.store = store

    def __call__(self, obj: Resource) -> None:
        if not isinstance(obj, Pod):
            return
        if obj.metadata.annotations.get(WEBHOOK_EXCLUDE_ANNOTATION) == "true":
            # ref main.go:496-504 exclusion annotation
            return
        defaults = self._matching_defaults(obj)
        if defaults:
            self._check_conflicts(obj, defaults)
            for pd in defaults:
                self._apply(obj, pd)
        self._inject_tpu_env(obj)
        self._inject_pod_start_time(obj)

    def _inject_pod_start_time(self, pod: Pod) -> None:
        """Stamp admission time so utils/profiling can report
        pod-to-first-XLA-compile (the BASELINE north-star latency) from
        the actual pod start instead of falling back to process start."""
        import time as _time

        stamp = str(_time.time())
        for c in pod.spec.containers:
            if all(e.name != POD_START_TIME_ENV for e in c.env):
                c.env.append(EnvVar(name=POD_START_TIME_ENV, value=stamp))

    # -- selection (ref filterPodDefaults main.go:70-95) -------------------

    def _matching_defaults(self, pod: Pod) -> list[TpuPodDefault]:
        out = []
        for pd in self.store.list("TpuPodDefault", pod.metadata.namespace):
            if _labels_match(pod.metadata.labels, pd.spec.selector):
                out.append(pd)
        return sorted(out, key=lambda p: p.metadata.name)

    # -- conflict detection (ref safeToApplyPodDefaultsOnPod :99-133) ------

    def _check_conflicts(self, pod: Pod, defaults: list[TpuPodDefault]) -> None:
        # Volumes are pod-level; env/mounts are checked PER CONTAINER (the
        # reference checks safeToApplyPodDefaultsOnContainer per container —
        # pooling across containers would false-deny multi-container pods
        # whose containers legitimately differ).
        volumes: dict[str, str] = {v.name: v.pvc_name for v in pod.spec.volumes}
        per_container = [
            (
                {e.name: e.value for e in c.env},
                {m.mount_path: m.name for m in c.volume_mounts},
            )
            for c in pod.spec.containers
        ]
        for pd in defaults:
            for env, mounts in per_container:
                for e in pd.spec.env:
                    if e.name in env and env[e.name] != e.value:
                        raise AdmissionDenied(
                            f"TpuPodDefault {pd.metadata.name}: env {e.name} "
                            f"conflicts (existing={env[e.name]!r} "
                            f"default={e.value!r})"
                        )
                    env[e.name] = e.value
                for m in pd.spec.volume_mounts:
                    if m.mount_path in mounts and mounts[m.mount_path] != m.name:
                        raise AdmissionDenied(
                            f"TpuPodDefault {pd.metadata.name}: mount path "
                            f"{m.mount_path} conflicts"
                        )
                    mounts[m.mount_path] = m.name
            for v in pd.spec.volumes:
                if v.name in volumes and volumes[v.name] != v.pvc_name:
                    raise AdmissionDenied(
                        f"TpuPodDefault {pd.metadata.name}: volume {v.name} "
                        "conflicts with existing volume"
                    )
                volumes[v.name] = v.pvc_name

    # -- merge (ref applyPodDefaultsOnPod :369-427) ------------------------

    def _apply(self, pod: Pod, pd: TpuPodDefault) -> None:
        spec = pd.spec
        for v in spec.volumes:
            if all(v.name != x.name for x in pod.spec.volumes):
                pod.spec.volumes.append(v)
        for t in spec.tolerations:
            if all(
                (t.key, t.value, t.effect) != (x.key, x.value, x.effect)
                for x in pod.spec.tolerations
            ):
                pod.spec.tolerations.append(t)
        if spec.service_account and not pod.spec.service_account:
            pod.spec.service_account = spec.service_account
        for k, v in spec.annotations.items():
            pod.metadata.annotations.setdefault(k, v)
        for k, v in spec.labels.items():
            pod.metadata.labels.setdefault(k, v)
        for c in pod.spec.containers:
            have = {e.name for e in c.env}
            c.env.extend(e for e in spec.env if e.name not in have)
            have_mounts = {m.mount_path for m in c.volume_mounts}
            c.volume_mounts.extend(
                m for m in spec.volume_mounts if m.mount_path not in have_mounts
            )
            # ref setCommandAndArgs :453-468 — only when pod doesn't set them
            if spec.command and not c.command:
                c.command = list(spec.command)
            if spec.args and not c.args:
                c.args = list(spec.args)
        pod.metadata.annotations[
            PODDEFAULT_APPLIED_PREFIX + pd.metadata.name
        ] = str(pd.metadata.resource_version)

    # -- TPU env injection (the NCCL-free multi-host bootstrap) ------------

    def _inject_tpu_env(self, pod: Pod) -> None:
        labels = pod.metadata.labels
        gang = labels.get(GANG_NAME_LABEL)
        topo_name = labels.get(TOPOLOGY_LABEL)
        if not gang or not topo_name:
            return
        topo = SLICE_TOPOLOGIES.get(topo_name)
        if topo is None:
            raise AdmissionDenied(f"unknown TPU topology {topo_name!r}")
        num_slices = int(labels.get(NUM_SLICES_LABEL, "1"))
        size = int(labels.get(GANG_SIZE_LABEL, topo.hosts * num_slices))
        ordinal = int(labels.get(GANG_ORDINAL_LABEL, "0"))
        if num_slices < 1 or size < 1 or size % num_slices:
            # Same admission depth as the unknown-topology check: broken
            # gang labels must fail the pod, not emit env that splits
            # slices at the wrong boundaries.
            raise AdmissionDenied(
                f"gang size {size} not divisible into {num_slices} "
                f"slice(s) (labels {GANG_SIZE_LABEL}/{NUM_SLICES_LABEL} "
                "disagree)"
            )
        # From here: num_slices >= 1, size >= 1, size % num_slices == 0.
        if num_slices > 1 and size != topo.hosts * num_slices:
            # Multi-slice env is derived from ordinal arithmetic: a size
            # that isn't hosts-per-slice x num_slices would emit
            # TPU_WORKER_HOSTNAMES lists that split real slices and
            # libtpu would wait forever for workers that never register.
            raise AdmissionDenied(
                f"gang size {size} != {topo.hosts} hosts/slice x "
                f"{num_slices} slices for topology {topo.name}"
            )
        ns = pod.metadata.namespace

        def dns(i: int) -> str:
            # Stable per-host DNS via the gang's headless service:
            # <gang>-<i>.<gang>.<ns>.svc (StatefulSet hostname contract).
            return f"{gang}-{i}.{gang}.{ns}.svc"

        # libtpu's worker env is PER SLICE: each slice is its own ICI
        # domain, so TPU_WORKER_ID/HOSTNAMES enumerate only slice-mates.
        # The JAX process group (and its coordinator) stays GLOBAL across
        # all slices — that is what lets jax.distributed + the hybrid
        # dcn mesh treat the job as one SPMD program with DCN between
        # slices (SURVEY.md §2b "DCN for cross-slice via JAX multi-slice
        # env"; env-merge mechanism per ref admission-webhook
        # main.go:153-188).
        hosts_per_slice = size // num_slices
        slice_id = ordinal // hosts_per_slice
        slice_base = slice_id * hosts_per_slice
        hostnames = ",".join(
            dns(slice_base + i) for i in range(hosts_per_slice)
        )
        coordinator = f"{dns(0)}:{JAX_COORDINATOR_PORT}"
        tpu_env = {
            "TPU_WORKER_ID": str(ordinal - slice_base),
            "TPU_WORKER_HOSTNAMES": hostnames,
            "TPU_CHIPS_PER_HOST_BOUNDS": _chips_per_host_bounds(topo),
            "TPU_ACCELERATOR_TYPE": topo.name,
            "JAX_COORDINATOR_ADDRESS": coordinator,
            "KFTPU_TOPOLOGY": topo.name,
            "KFTPU_NUM_PROCESSES": str(size),
            # The GLOBAL process id for jax.distributed.initialize —
            # distinct from TPU_WORKER_ID, which is per-slice for libtpu
            # and therefore repeats across slices in a multi-slice gang.
            "KFTPU_PROCESS_ID": str(ordinal),
        }
        if num_slices > 1:
            tpu_env.update({
                "MEGASCALE_NUM_SLICES": str(num_slices),
                "MEGASCALE_SLICE_ID": str(slice_id),
                "MEGASCALE_COORDINATOR_ADDRESS":
                    f"{dns(0)}:{MEGASCALE_COORDINATOR_PORT}",
                "KFTPU_NUM_SLICES": str(num_slices),
            })
        mesh = labels.get(MESH_LABEL, "")
        if mesh:
            tpu_env["KFTPU_MESH"] = mesh.replace("_", ",")
        for c in pod.spec.containers:
            have = {e.name for e in c.env}
            for k, v in tpu_env.items():
                if k not in have:
                    c.env.append(EnvVar(name=k, value=v))


def _chips_per_host_bounds(topo) -> str:
    """libtpu's per-host chip grid, e.g. '2,2,1' for 4 chips/host."""
    cph = topo.chips_per_host
    if cph == 1:
        return "1,1,1"
    if cph == 4:
        return "2,2,1"
    if cph == 8:
        return "2,4,1"
    return f"{cph},1,1"
