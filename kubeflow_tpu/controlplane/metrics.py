"""Prometheus-style metrics: registry, counters/gauges, live collectors.

Capability parity with the reference's three metric surfaces:
- notebook metrics collector that scrapes live state at collect time
  (ref notebook-controller/pkg/metrics/metrics.go:22-99 — a custom
  Collect() lists StatefulSets with the notebook-name label instead of
  maintaining a gauge imperatively), plus created/culled counters;
- profile reconcile counters with component/kind/severity labels
  (ref profile-controller/controllers/monitoring.go:19-77);
- KFAM request counters + a /metrics route
  (ref access-management/kfam/monitoring.go, routers.go:82-86).

No prometheus_client dependency: exposition is the stable text format,
rendered directly. Collectors are callables run at scrape time, so the
"running notebooks" gauge can never drift from the store's truth.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable

from kubeflow_tpu.controlplane.store import Store
from kubeflow_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    Histogram,
    format_float,
)


def _escape_label_value(v: str) -> str:
    # Prometheus exposition format: backslash, double-quote and newline
    # must be escaped inside label values.
    return (
        v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    """Monotonic counter with optional labels."""

    def __init__(self, name: str, help: str, registry: "Registry | None" = None):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: dict[tuple[tuple[str, str], ...], float] = {}
        if registry is not None:
            registry.register(self)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        # Under the lock: a bare dict read races concurrent inc/set
        # rehashing the table (CPython mostly saves us, but "mostly" is
        # not a memory model — and PEP 703 builds drop the GIL).
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def samples(self) -> Iterable[tuple[dict[str, str], float]]:
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]

    def expositions(self) -> Iterable[tuple[str, dict[str, str], float]]:
        """(sample_name, labels, value) in exposition order — the one
        render protocol shared with obs.metrics.Histogram (which emits
        _bucket/_sum/_count under this same hook)."""
        for labels, v in sorted(self.samples(),
                                key=lambda s: sorted(s[0].items())):
            yield self.name, labels, v

    TYPE = "counter"


class Gauge(Counter):
    TYPE = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value


class Registry:
    """Holds metrics and scrape-time collectors; renders exposition text."""

    def __init__(self):
        self._metrics: list[Counter] = []
        self._collectors: list[Callable[[], None]] = []
        self._lock = threading.Lock()

    def register(self, metric: Counter) -> None:
        with self._lock:
            if any(m.name == metric.name for m in self._metrics):
                raise ValueError(
                    f"metric {metric.name!r} already registered")
            self._metrics.append(metric)

    def get(self, name: str):
        """The registered metric named `name`, or None — the
        get-or-create hook obs.get_or_create_histogram builds on."""
        with self._lock:
            for m in self._metrics:
                if m.name == name:
                    return m
        return None

    def register_collector(self, fn: Callable[[], None]) -> None:
        """`fn` refreshes gauges from live state; runs on every render
        (the reference's custom Collect→scrape pattern)."""
        with self._lock:
            self._collectors.append(fn)

    def render(self) -> str:
        with self._lock:
            collectors = list(self._collectors)
            metrics = list(self._metrics)
        for fn in collectors:
            fn()
        lines: list[str] = []
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.TYPE}")
            # No samples yet → emit nothing (a synthetic unlabeled 0 would
            # create a timeseries that goes stale once labeled samples
            # appear; prometheus_client behaves the same way).
            for name, labels, v in m.expositions():
                lines.append(
                    f"{name}{_fmt_labels(labels)} {format_float(v)}")
        return "\n".join(lines) + "\n"


class ControlPlaneMetrics:
    """The platform's metric set, wired into controllers at assembly.

    Names keep the reference's vocabulary (notebook_create_total,
    notebook_cull_total, running gauge scraped live; reconcile counters
    labeled kind/severity).
    """

    def __init__(self, store: Store, registry: Registry | None = None):
        self.registry = registry or Registry()
        self.store = store
        self.notebooks_running = Gauge(
            "notebook_running", "Current running notebooks per namespace "
            "(scraped live from StatefulSets, ref metrics.go:74-99)",
            self.registry)
        self.tpu_hosts_running = Gauge(
            "tpu_hosts_running", "Current TPU-slice host pods per namespace",
            self.registry)
        self.notebook_created = Counter(
            "notebook_create_total", "Notebook StatefulSets created",
            self.registry)
        self.notebook_culled = Counter(
            "notebook_cull_total", "Notebooks culled for idleness",
            self.registry)
        self.reconcile_total = Counter(
            "reconcile_total", "Reconcile outcomes by controller kind "
            "(ref monitoring.go:62-77)", self.registry)
        self.request_total = Counter(
            "request_total", "HTTP requests by service/method/code "
            "(ref kfam/monitoring.go)", self.registry)
        # Latency layer (ISSUE 1): the reference never measured how long
        # anything took; these three are the control plane's hot paths.
        self.reconcile_duration = Histogram(
            "reconcile_duration_seconds",
            "Reconcile wall time by controller kind", self.registry,
            buckets=LATENCY_BUCKETS)
        self.workqueue_latency = Histogram(
            "workqueue_queue_latency_seconds",
            "Time a key waited in a controller workqueue before a "
            "worker picked it up", self.registry,
            buckets=LATENCY_BUCKETS)
        self.workqueue_depth = Gauge(
            "workqueue_depth",
            "Keys waiting (ready + delayed) per controller workqueue",
            self.registry)
        self.request_duration = Histogram(
            "request_duration_seconds",
            "Platform HTTP request latency by service/method",
            self.registry, buckets=LATENCY_BUCKETS)
        self.registry.register_collector(self._scrape)

    def _scrape(self) -> None:
        """Live scrape (never drifts): running notebooks = STS with the
        notebook-name label and ready replicas; TPU hosts = their pods."""
        running: dict[str, int] = {}
        hosts: dict[str, int] = {}
        for sts in self.store.list("StatefulSet"):
            if "notebook-name" not in sts.metadata.labels:
                continue
            ns = sts.metadata.namespace
            if sts.ready_replicas > 0:
                running[ns] = running.get(ns, 0) + 1
                if sts.spec.gang:
                    hosts[ns] = hosts.get(ns, 0) + sts.ready_replicas
        # Reset namespaces that emptied out, then set current values.
        for labels, _ in self.notebooks_running.samples():
            self.notebooks_running.set(
                float(running.get(labels.get("namespace", ""), 0)), **labels)
        for ns, n in running.items():
            self.notebooks_running.set(float(n), namespace=ns)
        for labels, _ in self.tpu_hosts_running.samples():
            self.tpu_hosts_running.set(
                float(hosts.get(labels.get("namespace", ""), 0)), **labels)
        for ns, n in hosts.items():
            self.tpu_hosts_running.set(float(n), namespace=ns)

    # -- hooks for controllers --------------------------------------------

    def record_reconcile(self, kind: str, ok: bool, *,
                         severity: str | None = None) -> None:
        """severity overrides the ok→info/error mapping (e.g. "conflict"
        for optimistic-concurrency retries, which are neither)."""
        self.reconcile_total.inc(
            kind=kind,
            severity=severity or ("info" if ok else "error"))

    def record_reconcile_duration(self, kind: str, seconds: float) -> None:
        self.reconcile_duration.observe(seconds, kind=kind)

    def record_queue_latency(self, kind: str, seconds: float) -> None:
        self.workqueue_latency.observe(seconds, kind=kind)

    def record_request(self, service: str, method: str, code: int,
                       seconds: float | None = None) -> None:
        self.request_total.inc(service=service, method=method,
                               code=str(code))
        if seconds is not None:
            self.request_duration.observe(seconds, service=service,
                                          method=method)


def scan_usage(store: Store) -> tuple[list[tuple[str, str]],
                                      dict[str, int]]:
    """One store walk shared by the dashboard summary and the history
    sampler (a drifted copy of the 'TPU host in use' filter would
    silently desynchronize the summary tiles from the chart's live
    point): [(namespace, topology)] per running TPU-host pod, plus
    notebooks per namespace."""
    from kubeflow_tpu.controlplane import webhook as wh

    pods: list[tuple[str, str]] = []
    nbs: dict[str, int] = {}
    for pod in store.list("Pod"):
        topo = pod.metadata.labels.get(wh.TOPOLOGY_LABEL)
        if topo and pod.phase == "Running":
            pods.append((pod.metadata.namespace, topo))
    for nb in store.list("Notebook"):
        ns = nb.metadata.namespace
        nbs[ns] = nbs.get(ns, 0) + 1
    return pods, nbs


class MetricsHistory:
    """Ring-buffered cluster-usage time series for the dashboard charts.

    The reference's dashboard serves cluster resource charts over
    5/15/30/60/180-minute windows from Stackdriver
    (ref centraldashboard/app/metrics_service.ts:2-8, routes
    api.ts:29-102, impl stackdriver_metrics_service.ts:15-60). The
    TPU-native platform has no cloud monitoring dependency, so the
    history lives here: periodic samples of per-namespace TPU-host and
    notebook counts scanned from the store, kept per NAMESPACE so the
    serving endpoint can apply the same visibility scoping as the
    point-in-time summary (cluster-wide series would leak cross-tenant
    occupancy to non-admins).
    """

    WINDOWS_MIN = (5, 15, 30, 60, 180)

    def __init__(self, store: Store, *, cadence_s: float = 30.0,
                 clock: Callable[[], float] | None = None):
        import collections
        import time as _time

        self.store = store
        self.cadence_s = cadence_s
        self._clock = clock or _time.time
        # retention = the longest window + one slack sample
        self._samples: collections.deque = collections.deque(
            maxlen=int(self.WINDOWS_MIN[-1] * 60 / cadence_s) + 2)
        self._lock = threading.Lock()

    def _scan(self) -> tuple[dict[str, int], dict[str, int]]:
        pods, nbs = scan_usage(self.store)
        tpu: dict[str, int] = {}
        for ns, _topo in pods:
            tpu[ns] = tpu.get(ns, 0) + 1
        return tpu, nbs

    def sample(self) -> None:
        """Scan the store once and append a ring point. Calls within
        half a cadence collapse to one sample, so the ring fills at
        CADENCE rate and its retention math holds even if multiple
        samplers ever run. The dashboard's background task is the ONLY
        caller today; request-time freshness is series(live=...),
        which never stores."""
        now = self._clock()
        with self._lock:
            if self._samples and \
                    now - self._samples[-1][0] < self.cadence_s / 2:
                return
            tpu, nbs = self._scan()
            self._samples.append((now, tpu, nbs))

    def series(self, window_min: int,
               visible: set[str] | None = None,
               live: "bool | tuple" = False) -> list[dict]:
        """Points within the window, each summed over `visible`
        namespaces (None = cluster-wide, the admin view). `live`
        appends a now-point WITHOUT storing it, so a chart always ends
        at the present even between cadence ticks — True scans here; a
        (tpu_by_ns, notebooks_by_ns) tuple reuses a scan the caller
        already paid for (the dashboard handler's summary walk)."""
        if window_min not in self.WINDOWS_MIN:
            raise ValueError(
                f"window must be one of {self.WINDOWS_MIN} minutes")
        if not isinstance(live, bool) and not (
                isinstance(live, (tuple, list)) and len(live) == 2
                and all(isinstance(d, dict) for d in live)):
            # Without this check a malformed tuple surfaces as an
            # opaque TypeError deep inside pt() — name the contract.
            raise ValueError(
                "live must be True, False, or a (tpu_by_namespace, "
                "notebooks_by_namespace) pair of dicts")
        now = self._clock()
        cutoff = now - window_min * 60

        def pt(t, tpu, nbs):
            return {
                "t": round(t, 3),
                "tpuHostsInUse": sum(
                    n for ns, n in tpu.items()
                    if visible is None or ns in visible),
                "notebooks": sum(
                    n for ns, n in nbs.items()
                    if visible is None or ns in visible),
            }

        with self._lock:
            pts = [pt(t, tpu, nbs)
                   for t, tpu, nbs in self._samples if t >= cutoff]
            if live is True:
                pts.append(pt(now, *self._scan()))
            elif live:
                pts.append(pt(now, *live))
        return pts
