"""Access management service (KFAM): profiles + contributor bindings.

Re-design of the reference's access-management component (kfam/*.go):
- profile create/delete (api_default.go:134-155 → profile CR);
- contributor bindings: a RoleBinding + AuthorizationPolicy-users pair
  per contributor (bindings.go:96-139), listed back from RoleBinding
  annotations (bindings.go:179-222);
- owner-or-cluster-admin permission gate on mutations
  (api_default.go:104-132, :293-310);
- role mapping admin|edit|view ↔ cluster role names
  (api_default.go:39-46).

The REST surface (aiohttp app in kubeflow_tpu.web.kfam_app) wraps this
logic; tests drive both layers.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass

from kubeflow_tpu.api.core import RoleBinding
from kubeflow_tpu.api.crds import Profile
from kubeflow_tpu.controlplane.auth import (
    User,
    is_cluster_admin,
    is_reserved_namespace,
)
from kubeflow_tpu.controlplane.controllers.profile import (
    OWNER_ANNOTATION,
    ROLE_ADMIN,
    ROLE_EDIT,
    ROLE_VIEW,
)
from kubeflow_tpu.controlplane.store import AlreadyExists, NotFound, Store

_ROLE_MAP = {"admin": ROLE_ADMIN, "edit": ROLE_EDIT, "view": ROLE_VIEW}
_ROLE_UNMAP = {v: k for k, v in _ROLE_MAP.items()}

_EMAIL_RE = re.compile(r"^[^@\s]+@[^@\s]+$|^sa:[\w.-]+:[\w.-]+$")
# RFC-1123 label: a Profile's name becomes its namespace's name. Public
# so every profile-creating door (KFAM, /apis/) applies the same rule.
PROFILE_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")
_NAME_RE = PROFILE_NAME_RE


class KfamError(Exception):
    status = 400


class PermissionDenied(KfamError):
    status = 403


@dataclass
class Binding:
    user: str
    namespace: str
    role: str  # admin | edit | view


class Kfam:
    def __init__(self, store: Store, cluster_admins: set[str] | None = None):
        self.store = store
        self.cluster_admins = cluster_admins or set()

    # -- permission gate (ref api_default.go:104-132) ----------------------

    def _ensure_owner_or_admin(self, caller: User, namespace: str) -> None:
        if is_cluster_admin(self.store, caller, self.cluster_admins):
            return
        profile = self.store.try_get("Profile", "", namespace)
        if profile is not None and profile.spec.owner == caller.name:
            return
        # namespace admins (contributors with admin role) also qualify
        for rb in self.store.list("RoleBinding", namespace):
            if caller.name in rb.subjects and rb.role == ROLE_ADMIN:
                return
        raise PermissionDenied(
            f"{caller.name} is not owner/admin of {namespace}"
        )

    # -- profiles ----------------------------------------------------------

    def create_profile(self, caller: User, name: str, owner: str = "",
                       quota: dict[str, str] | None = None) -> Profile:
        owner = owner or caller.name
        if owner != caller.name and not is_cluster_admin(
            self.store, caller, self.cluster_admins
        ):
            raise PermissionDenied("only cluster admins create for others")
        if not _NAME_RE.match(name):
            raise KfamError(f"invalid profile name {name!r}")
        if is_reserved_namespace(name):
            raise PermissionDenied(f"namespace name {name!r} is reserved")
        p = Profile()
        p.metadata.name = name
        p.spec.owner = owner
        if quota:
            p.spec.resource_quota = dict(quota)
        try:
            return self.store.create(p)
        except AlreadyExists:
            raise KfamError(f"profile {name} already exists")

    def delete_profile(self, caller: User, name: str) -> None:
        self._ensure_owner_or_admin(caller, name)
        try:
            self.store.delete("Profile", "", name)
        except NotFound:
            raise KfamError(f"profile {name} not found")

    # -- bindings (ref bindings.go:96-222) ---------------------------------

    def create_binding(self, caller: User, b: Binding) -> None:
        self._ensure_owner_or_admin(caller, b.namespace)
        if b.role not in _ROLE_MAP:
            raise KfamError(f"unknown role {b.role!r} (admin|edit|view)")
        if not _EMAIL_RE.match(b.user):
            raise KfamError(f"invalid user {b.user!r}")
        rb = RoleBinding(role=_ROLE_MAP[b.role], subjects=[b.user])
        rb.metadata.name = _binding_name(b.user, b.role)
        rb.metadata.namespace = b.namespace
        rb.metadata.annotations["user"] = b.user
        rb.metadata.annotations["role"] = b.role
        try:
            self.store.create(rb)
        except AlreadyExists:
            raise KfamError(f"binding for {b.user} already exists")
        self._sync_authz_users(b.namespace)

    def delete_binding(self, caller: User, b: Binding) -> None:
        self._ensure_owner_or_admin(caller, b.namespace)
        try:
            self.store.delete(
                "RoleBinding", b.namespace, _binding_name(b.user, b.role)
            )
        except NotFound:
            raise KfamError(f"binding for {b.user} not found")
        self._sync_authz_users(b.namespace)

    def list_bindings(self, caller: User, namespace: str | None = None,
                      user: str | None = None) -> list[Binding]:
        out = []
        for rb in self.store.list("RoleBinding", namespace):
            u = rb.metadata.annotations.get("user")
            r = rb.metadata.annotations.get("role") or _ROLE_UNMAP.get(rb.role)
            if not u or not r:
                continue  # not a kfam-managed binding
            if user is not None and u != user:
                continue
            out.append(Binding(user=u, namespace=rb.metadata.namespace, role=r))
        return out

    def is_cluster_admin(self, user: User) -> bool:
        return is_cluster_admin(self.store, user, self.cluster_admins)

    def _sync_authz_users(self, namespace: str) -> None:
        """Keep the namespace AuthorizationPolicy's user list in step with
        bindings (the reference creates a per-contributor policy,
        bindings.go:79-94; we maintain one policy's allow list)."""
        ap = self.store.try_get("AuthorizationPolicy", namespace,
                                "ns-owner-access")
        if ap is None:
            return
        users = {
            u for rb in self.store.list("RoleBinding", namespace)
            for u in rb.subjects
        }
        profile = self.store.try_get("Profile", "", namespace)
        if profile is not None:
            users.add(profile.spec.owner)
        users = sorted(users)
        if ap.allow_users != users:
            ap.allow_users = users
            self.store.update(ap)


def _binding_name(user: str, role: str) -> str:
    digest = hashlib.sha256(f"{user}:{role}".encode()).hexdigest()[:10]
    return f"contributor-{digest}"
