"""Data layer: native token-shard loader with a pure-Python fallback."""

from kubeflow_tpu.data.loader import (
    PyTokenLoader,
    TokenShardLoader,
    native_available,
    open_loader,
    write_shard,
)
