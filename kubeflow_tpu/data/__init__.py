"""Data layer: BPE tokenizer + native token-shard loader (with a
pure-Python fallback)."""

from kubeflow_tpu.data.bpe import Tokenizer, train as train_tokenizer
from kubeflow_tpu.data.loader import (
    PyTokenLoader,
    TokenShardLoader,
    native_available,
    open_loader,
    write_shard,
)
