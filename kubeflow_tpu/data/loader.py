"""Token-shard data loading: ctypes bindings over the native loader.

`native/dataloader.cpp` owns the hot path (mmap shards, prefetch thread
pool); this module is the thin Python face plus a bit-identical pure-
Python fallback (`PyTokenLoader`) used when no C++ toolchain exists. The
shuffle is a shared deterministic LCG Fisher-Yates, so the two
implementations produce the SAME batch stream for the same
(seed, epoch, host) — swapping loaders never changes training data order
(parity is tested in tests/test_dataloader.py).

Shard format "KTSH": magic u32 | version u32 | n_tokens u64 | int32[].
Multi-host: (host, n_hosts) stripes the shuffled window order the way
TPU_WORKER_ID stripes the gang — each host sees a disjoint window set.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from typing import Iterator, Sequence

import numpy as np

MAGIC = 0x4853544B  # "KTSH"
VERSION = 1
_HEADER = struct.Struct("<IIQ")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libktdata.so")

_lib: ctypes.CDLL | None = None
_build_failed = False


def write_shard(path: str, tokens: np.ndarray) -> None:
    """Write an int32 token array as a KTSH shard."""
    arr = np.ascontiguousarray(tokens, dtype=np.int32)
    with open(path, "wb") as f:
        f.write(_HEADER.pack(MAGIC, VERSION, arr.size))
        f.write(arr.tobytes())


def ensure_built() -> bool:
    """Build libktdata.so if missing OR stale vs its source; returns
    availability. The staleness check matters: loading a .so built
    before an ABI change (e.g. kt_loader_open gaining start_ticket)
    would read garbage arguments instead of failing loudly."""
    global _build_failed
    if _build_failed:
        return False
    src = os.path.join(_NATIVE_DIR, "dataloader.cpp")
    if not os.path.exists(src):
        _build_failed = not os.path.exists(_LIB_PATH)
        return not _build_failed
    if (os.path.exists(_LIB_PATH)
            and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(src)):
        return True
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR, "libktdata.so"],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        _build_failed = True
        return False


_ABI_VERSION = 2  # must match native/dataloader.cpp kt_abi_version()


def _load_lib() -> ctypes.CDLL | None:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if not ensure_built():
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    # ABI gate: the mtime staleness check cannot protect a prebuilt
    # .so shipped WITHOUT its source (deployed wheels); calling a
    # 9-arg kt_loader_open with 10 arguments would silently misread
    # seed/host/prefetch instead of failing loudly.
    try:
        lib.kt_abi_version.restype = ctypes.c_uint64
        abi = int(lib.kt_abi_version())
    except AttributeError:
        abi = 1  # predates the version export
    if abi != _ABI_VERSION:
        import logging

        logging.getLogger(__name__).warning(
            "libktdata.so ABI %d != expected %d; using the Python "
            "loader (rebuild native/)", abi, _ABI_VERSION)
        _build_failed = True
        return None
    lib.kt_loader_open.restype = ctypes.c_void_p
    lib.kt_loader_open.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
    ]
    lib.kt_loader_next.restype = ctypes.c_int
    lib.kt_loader_next.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_int32)]
    lib.kt_loader_n_windows.restype = ctypes.c_uint64
    lib.kt_loader_n_windows.argtypes = [ctypes.c_void_p]
    lib.kt_loader_close.argtypes = [ctypes.c_void_p]
    lib.kt_last_error.restype = ctypes.c_char_p
    _lib = lib
    return lib


def native_available() -> bool:
    return _load_lib() is not None


class TokenShardLoader:
    """Native loader handle. Iterate with next_batch() -> [b, seq+1] i32.

    `start_ticket`/`state_dict()` are the checkpoint/resume pair:
    batches are pure functions of a dense ticket, so persisting the
    ticket alongside the TrainState (Checkpointer's data_state item)
    and reopening at it reproduces the uninterrupted batch stream."""

    def __init__(self, paths: Sequence[str], *, batch: int, seq: int,
                 seed: int = 0, host: int = 0, n_hosts: int = 1,
                 prefetch: int = 4, threads: int = 2,
                 start_ticket: int = 0):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError(
                "native loader unavailable (no toolchain?); use "
                "PyTokenLoader or open_loader()")
        self._lib = lib
        self.batch, self.seq = batch, seq
        if start_ticket < 0:
            raise ValueError(f"start_ticket must be >= 0, got "
                             f"{start_ticket}")
        self.ticket = start_ticket  # batches consumed since ticket 0
        c_paths = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths])
        self._h = lib.kt_loader_open(
            c_paths, len(paths), batch, seq, seed, host, n_hosts,
            prefetch, threads, start_ticket)
        if not self._h:
            raise ValueError(
                f"kt_loader_open: {lib.kt_last_error().decode()}")

    @property
    def n_windows(self) -> int:
        return int(self._lib.kt_loader_n_windows(self._h))

    def state_dict(self) -> dict:
        return {"ticket": self.ticket}

    def next_batch(self) -> np.ndarray:
        # Fresh buffer per call: the C side memcpys straight into it —
        # exactly one copy from the prefetched batch to Python.
        out = np.empty((self.batch, self.seq + 1), np.int32)
        rc = self._lib.kt_loader_next(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise RuntimeError("loader closed")
        self.ticket += 1
        return out

    def close(self) -> None:
        if self._h:
            self._lib.kt_loader_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next_batch()


# -- pure-Python fallback (bit-identical order) -----------------------------


def _lcg_shuffle(n: int, seed: int, epoch: int) -> np.ndarray:
    """Fisher-Yates driven by the SAME LCG as the C++ loader."""
    perm = np.arange(n, dtype=np.uint64)
    mask = (1 << 64) - 1
    state = (seed ^ ((epoch * 0x9E3779B97F4A7C15) & mask)) & mask
    for i in range(n, 1, -1):
        state = (state * 6364136223846793005 + 1442695040888963407) & mask
        j = (state >> 33) % i
        perm[i - 1], perm[j] = perm[j], perm[i - 1]
    return perm


class PyTokenLoader:
    """Same semantics as TokenShardLoader, no native dependency."""

    def __init__(self, paths: Sequence[str], *, batch: int, seq: int,
                 seed: int = 0, host: int = 0, n_hosts: int = 1,
                 start_ticket: int = 0, **_ignored):
        if not paths or batch < 1 or seq < 1 or not (0 <= host < n_hosts):
            raise ValueError("invalid arguments")
        if start_ticket < 0:
            raise ValueError(f"start_ticket must be >= 0, got "
                             f"{start_ticket}")
        self.batch, self.seq = batch, seq
        self.seed, self.host, self.n_hosts = seed, host, n_hosts
        self._shards: list[np.ndarray] = []
        self._window_base: list[int] = []
        total = 0
        for p in paths:
            with open(p, "rb") as f:
                magic, version, n_tokens = _HEADER.unpack(
                    f.read(_HEADER.size))
                if magic != MAGIC or version != VERSION:
                    raise ValueError(f"bad shard {p}")
                toks = np.fromfile(f, dtype=np.int32, count=n_tokens)
                if toks.size != n_tokens:
                    raise ValueError(f"truncated shard {p}")
            self._shards.append(toks)
            self._window_base.append(total)
            total += max(0, (n_tokens - 1) // seq)
        self._total_windows = total
        self.n_windows = total // n_hosts
        self._batches_per_epoch = self.n_windows // batch
        if self._batches_per_epoch == 0:
            raise ValueError("not enough windows for one batch")
        self.ticket = start_ticket
        self._cached_epoch = -1
        self._order: np.ndarray | None = None

    def state_dict(self) -> dict:
        return {"ticket": self.ticket}

    def _window(self, global_w: int) -> np.ndarray:
        si = 0
        while (si + 1 < len(self._window_base)
               and self._window_base[si + 1] <= global_w):
            si += 1
        local = global_w - self._window_base[si]
        start = local * self.seq
        return self._shards[si][start:start + self.seq + 1]

    def next_batch(self) -> np.ndarray:
        epoch = self.ticket // self._batches_per_epoch
        b = self.ticket % self._batches_per_epoch
        self.ticket += 1
        if epoch != self._cached_epoch:
            perm = _lcg_shuffle(self._total_windows, self.seed, epoch)
            self._order = perm[self.host::self.n_hosts]
            self._cached_epoch = epoch
        out = np.empty((self.batch, self.seq + 1), np.int32)
        for i in range(self.batch):
            out[i] = self._window(int(self._order[b * self.batch + i]))
        return out

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next_batch()


def open_loader(paths: Sequence[str], **kwargs):
    """Native when available, Python otherwise — same batch stream."""
    if native_available():
        return TokenShardLoader(paths, **kwargs)
    return PyTokenLoader(paths, **kwargs)
