"""Byte-level BPE tokenizer: train, encode, decode, persist.

Completes the text pipeline between raw corpora and the token-shard
loader (data.loader) / serving engine: byte-level base alphabet (every
UTF-8 string tokenizes — no OOV, no unicode normalization questions),
greedy rank-ordered merges learned from a corpus, JSON persistence.

Design notes:
- Training is the classic pair-counting loop over a word frequency
  table (split on whitespace boundaries like GPT-2's regex, simplified:
  leading-space word convention keeps word boundaries reversible), with
  counts updated incrementally only for words containing the merged
  pair — O(unique words) per merge, not O(corpus).
- Encoding applies merges by rank (lowest first), the standard greedy
  BPE; a merge-rank dict makes each word O(pieces^2) worst case with
  tiny constants, and an LRU memo makes hot words O(1).
- IDs: 0..255 are the raw bytes, then one id per merge, then specials
  appended at the end (pad/bos/eos by default) — so a trained tokenizer
  of V merges has vocab 256 + V + len(specials), matching how serving's
  EngineConfig.eos_token expects a real id.

The reference has no tokenizer (it has no compute at all, SURVEY.md
§2b); serving/server.py's byte_encode remains the zero-training
fallback and uses the same bytes-first convention.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
from collections import Counter
from typing import Iterable, Sequence

DEFAULT_SPECIALS = ("<pad>", "<bos>", "<eos>")


def _to_word_bytes(word: str) -> tuple[int, ...]:
    return tuple(word.encode("utf-8"))


# Longest word the greedy encoder will process whole. Space-free runs
# (CJK prose, URLs, base64 blobs) otherwise become ONE word, making
# encode O(bytes^2) in the run length and stuffing unbounded-size
# entries into the LRU — the server's text mode exposes that to
# clients. Chunking preserves exact decode (concatenation) and costs
# only the merges that would have crossed a chunk boundary.
_MAX_WORD_CHARS = 128


def _split_words(text: str) -> list[str]:
    """Leading-space word convention: "a b" -> ["a", " b"] — boundaries
    survive tokenization, so decode is exact concatenation. Words longer
    than _MAX_WORD_CHARS are chunked (see note above)."""
    out: list[str] = []

    def push(word: str) -> None:
        for i in range(0, len(word), _MAX_WORD_CHARS):
            out.append(word[i:i + _MAX_WORD_CHARS])

    start = 0
    for i in range(1, len(text)):
        if text[i] == " " and text[i - 1] != " ":
            push(text[start:i])
            start = i
    if text:
        push(text[start:])
    return out


@dataclasses.dataclass(frozen=True)
class Tokenizer:
    """Immutable trained tokenizer. Build with `train` or `load`."""

    merges: tuple[tuple[int, int], ...]   # (left_id, right_id) by rank
    specials: tuple[str, ...] = DEFAULT_SPECIALS

    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges) + len(self.specials)

    def special_id(self, token: str) -> int:
        return 256 + len(self.merges) + self.specials.index(token)

    @property
    def eos_id(self) -> int:
        return self.special_id("<eos>")

    @property
    def bos_id(self) -> int:
        return self.special_id("<bos>")

    @property
    def pad_id(self) -> int:
        return self.special_id("<pad>")

    @functools.cached_property
    def _ranks(self) -> dict[tuple[int, int], int]:
        return {pair: i for i, pair in enumerate(self.merges)}

    @functools.cached_property
    def _decode_table(self) -> dict[int, bytes]:
        table = {i: bytes([i]) for i in range(256)}
        for rank, (a, b) in enumerate(self.merges):
            table[256 + rank] = table[a] + table[b]
        return table

    def _encode_word(self, word: tuple[int, ...]) -> list[int]:
        return _encode_word_cached(self._ranks_id, word)

    @functools.cached_property
    def _ranks_id(self):
        # A hashable capsule for the lru-cached module function: the
        # tokenizer is immutable, so identity keying is sound.
        return _RanksHandle(self._ranks, self.merges)

    def encode(self, text: str, *, bos: bool = False,
               eos: bool = False) -> list[int]:
        ids: list[int] = [self.bos_id] if bos else []
        for word in _split_words(text):
            ids.extend(self._encode_word(_to_word_bytes(word)))
        if eos:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        table = self._decode_table
        n_text = 256 + len(self.merges)
        data = b"".join(table[i] for i in ids if 0 <= i < n_text)
        return data.decode("utf-8", errors="replace")

    # -- persistence -------------------------------------------------------

    def dumps(self) -> str:
        return json.dumps({
            "version": 1,
            "merges": [list(m) for m in self.merges],
            "specials": list(self.specials),
        })

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.dumps())

    @classmethod
    def loads(cls, data: str) -> "Tokenizer":
        obj = json.loads(data)
        if obj.get("version") != 1:
            raise ValueError(f"unknown tokenizer version {obj.get('version')}")
        return cls(
            merges=tuple((int(a), int(b)) for a, b in obj["merges"]),
            specials=tuple(obj["specials"]),
        )

    @classmethod
    def load(cls, path: str) -> "Tokenizer":
        with open(path) as f:
            return cls.loads(f.read())


class _RanksHandle:
    """Hashable capsule keying the word cache. Carries the rank table
    AND (lazily) the native encoder so the cached function can take the
    C++ path (native/bpe.cpp — bit-identical semantics, tested) without
    changing cache identity."""

    __slots__ = ("ranks", "merges", "_native")

    _NATIVE_UNSET = object()

    def __init__(self, ranks, merges=()):
        self.ranks = ranks
        self.merges = merges
        self._native = self._NATIVE_UNSET

    @property
    def native(self):
        if self._native is self._NATIVE_UNSET:
            self._native = _native_encoder(self.merges)
        return self._native

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other


_bpe_build_failed = False


def _ensure_bpe_built() -> str | None:
    """Build libktbpe.so if missing (same lazy-make discipline as
    loader.ensure_built — a fresh checkout must reach the native path
    without a manual `make -C native`). Returns the lib path or None."""
    global _bpe_build_failed
    from kubeflow_tpu.data import loader as _loader

    native_dir = os.path.dirname(_loader._LIB_PATH)
    lib_path = os.path.join(native_dir, "libktbpe.so")
    if os.path.exists(lib_path):
        return lib_path
    if _bpe_build_failed:
        return None
    import subprocess

    try:
        subprocess.run(["make", "-C", native_dir, "libktbpe.so"],
                       check=True, capture_output=True, timeout=120)
    except Exception:  # noqa: BLE001 — no toolchain: fallback stays
        _bpe_build_failed = True
        return None
    return lib_path if os.path.exists(lib_path) else None


def _native_encoder(merges):
    """ctypes handle over native/bpe.cpp, or None (fallback stays)."""
    if not merges or os.environ.get("KFTPU_BPE_FORCE_PY"):
        return None
    import ctypes

    lib_path = _ensure_bpe_built()
    if lib_path is None:
        return None
    lib = ctypes.CDLL(lib_path)
    lib.kt_bpe_new.restype = ctypes.c_void_p
    lib.kt_bpe_new.argtypes = [ctypes.POINTER(ctypes.c_int32),
                               ctypes.c_int64]
    lib.kt_bpe_encode_word.restype = ctypes.c_int64
    lib.kt_bpe_encode_word.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32)]
    # Without explicit argtypes ctypes passes the handle as a C int —
    # 32-bit truncation of a 64-bit pointer segfaults in free().
    lib.kt_bpe_free.restype = None
    lib.kt_bpe_free.argtypes = [ctypes.c_void_p]
    flat = (ctypes.c_int32 * (2 * len(merges)))(
        *(x for pair in merges for x in pair))
    handle = lib.kt_bpe_new(flat, len(merges))

    class _Native:
        def __init__(self, lib, handle):
            self.lib = lib
            self.handle = handle

        def encode(self, word: tuple[int, ...]) -> tuple[int, ...]:
            n = len(word)
            buf_in = (ctypes.c_uint8 * n)(*word)
            buf_out = (ctypes.c_int32 * n)()
            count = self.lib.kt_bpe_encode_word(
                self.handle, buf_in, n, buf_out)
            return tuple(buf_out[:count])

        def __del__(self):
            try:
                self.lib.kt_bpe_free(self.handle)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass

    return _Native(lib, handle)


@functools.lru_cache(maxsize=65536)
def _encode_word_cached(handle: _RanksHandle,
                        word: tuple[int, ...]) -> tuple[int, ...]:
    # returns a tuple: the cache hands the SAME object to every caller
    native = handle.native
    if native is not None and word:
        return native.encode(word)
    ranks = handle.ranks
    pieces = list(word)
    while len(pieces) > 1:
        best_rank, best_i = None, -1
        for i in range(len(pieces) - 1):
            r = ranks.get((pieces[i], pieces[i + 1]))
            if r is not None and (best_rank is None or r < best_rank):
                best_rank, best_i = r, i
        if best_rank is None:
            break
        pieces[best_i:best_i + 2] = [256 + best_rank]
    return tuple(pieces)


def train(corpus: Iterable[str], *, vocab_size: int,
          specials: Sequence[str] = DEFAULT_SPECIALS) -> Tokenizer:
    """Learn merges until vocab_size = 256 + merges + specials (or the
    corpus runs out of repeated pairs)."""
    n_merges = vocab_size - 256 - len(specials)
    if n_merges < 0:
        raise ValueError(
            f"vocab_size {vocab_size} smaller than bytes+specials "
            f"({256 + len(specials)})")

    # word -> frequency, each word a tuple of current piece ids
    words: Counter[tuple[int, ...]] = Counter()
    for text in corpus:
        for w in _split_words(text):
            words[_to_word_bytes(w)] += 1

    pair_counts: Counter[tuple[int, int]] = Counter()
    for w, c in words.items():
        for pair in zip(w, w[1:]):
            pair_counts[pair] += c

    merges: list[tuple[int, int]] = []
    for _ in range(n_merges):
        if not pair_counts:
            break
        # deterministic: max count, ties by pair id order
        best = max(pair_counts.items(), key=lambda kv: (kv[1], (-kv[0][0], -kv[0][1])))
        pair, count = best
        if count < 2:
            break  # merging singletons only bloats the vocab
        new_id = 256 + len(merges)
        merges.append(pair)
        # Rewrite only the words containing the pair; update pair counts
        # incrementally (remove the word's old pairs, add its new ones).
        for w in [w for w in words if _contains_pair(w, pair)]:
            c = words.pop(w)
            for p in zip(w, w[1:]):
                pair_counts[p] -= c
                if pair_counts[p] <= 0:
                    del pair_counts[p]
            new_w = _merge_word(w, pair, new_id)
            words[new_w] += c
            for p in zip(new_w, new_w[1:]):
                pair_counts[p] += c
    return Tokenizer(merges=tuple(merges), specials=tuple(specials))


def _contains_pair(w: tuple[int, ...], pair: tuple[int, int]) -> bool:
    a, b = pair
    return any(w[i] == a and w[i + 1] == b for i in range(len(w) - 1))


def _merge_word(w: tuple[int, ...], pair: tuple[int, int],
                new_id: int) -> tuple[int, ...]:
    out: list[int] = []
    i = 0
    while i < len(w):
        if i + 1 < len(w) and (w[i], w[i + 1]) == pair:
            out.append(new_id)
            i += 2
        else:
            out.append(w[i])
            i += 1
    return tuple(out)
