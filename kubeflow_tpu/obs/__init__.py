"""Unified observability layer: histograms + in-process span tracing.

One import point for the three layers (control plane, serving, train):

    from kubeflow_tpu import obs
    with obs.DEFAULT_TRACER.span("reconcile", kind="Notebook"):
        ...
    obs.get_or_create_histogram(reg, "x_seconds", "...").observe(dt)

`Histogram` registers into the EXISTING controlplane Registry (or any
object with register()/get()); `Tracer` is standalone. The module-level
defaults exist for components with no natural registry/tracer owner
(the Trainer); apps that serve `/metrics` and `/debug/traces` should
own their instances and pass them down (Cluster does).

Import discipline: this package must not import controlplane at module
scope — controlplane.metrics imports `obs.metrics` for its own
histograms, and an eager reverse import would cycle. `default_registry`
imports lazily instead.
"""

from __future__ import annotations

from kubeflow_tpu.obs.cachestats import (
    DEFER_CAUSES,
    EVICTION_CAUSES,
    PEER_FETCH_OUTCOMES,
    PREFILL_SOURCES,
    REUSE_BUCKETS,
    UNATTRIBUTED,
    CacheLedger,
    canonical_prefix,
    prefix_hash,
)
from kubeflow_tpu.obs.cardinality import OVERFLOW_LABEL, LabelGuard
from kubeflow_tpu.obs.decisions import (
    OUTCOMES as DECISION_OUTCOMES,
    VERDICTS as DECISION_VERDICTS,
    DecisionLedger,
)
from kubeflow_tpu.obs.exposition import (
    ExpositionError,
    parse_exposition,
    render_families,
)
from kubeflow_tpu.obs.federation import federate, merge_families
from kubeflow_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    SIZE_BUCKETS,
    TOKEN_BUCKETS,
    Histogram,
    format_float,
    get_or_create_histogram,
    sample_quantile,
)
from kubeflow_tpu.obs.profiling import (
    SERVING_PHASES,
    TRAIN_PHASES,
    WATCHED_SERVING_FNS,
    WATCHED_TRAIN_FNS,
    CompileWatch,
    PhaseProfiler,
    abstract_signature,
    merge_counter_tracks,
)
from kubeflow_tpu.obs.slo import (
    Slo,
    SloBudgetGauge,
    SloEngine,
    get_or_create_slo_engine,
    register_budget_gauge,
)
from kubeflow_tpu.obs.timeline import RequestTimeline, TimelineStore
from kubeflow_tpu.obs.tracing import (
    Span,
    Tracer,
    merge_chrome_traces,
    traces_response_payload,
)

# obs.endpoints (the shared aiohttp /metrics + /debug/traces handlers)
# is deliberately NOT imported here: importing `obs` must not pull
# aiohttp into HTTP-free processes (the Trainer).

__all__ = [
    "DEFER_CAUSES",
    "EVICTION_CAUSES",
    "LATENCY_BUCKETS",
    "PEER_FETCH_OUTCOMES",
    "PREFILL_SOURCES",
    "REUSE_BUCKETS",
    "SIZE_BUCKETS",
    "TOKEN_BUCKETS",
    "SERVING_PHASES",
    "TRAIN_PHASES",
    "UNATTRIBUTED",
    "WATCHED_SERVING_FNS",
    "WATCHED_TRAIN_FNS",
    "CacheLedger",
    "CompileWatch",
    "DECISION_OUTCOMES",
    "DECISION_VERDICTS",
    "DecisionLedger",
    "ExpositionError",
    "Histogram",
    "LabelGuard",
    "OVERFLOW_LABEL",
    "PhaseProfiler",
    "RequestTimeline",
    "Slo",
    "SloBudgetGauge",
    "SloEngine",
    "Span",
    "TimelineStore",
    "Tracer",
    "DEFAULT_TRACER",
    "abstract_signature",
    "canonical_prefix",
    "default_registry",
    "federate",
    "format_float",
    "get_or_create_histogram",
    "get_or_create_slo_engine",
    "merge_chrome_traces",
    "merge_counter_tracks",
    "merge_families",
    "parse_exposition",
    "prefix_hash",
    "register_budget_gauge",
    "render_families",
    "sample_quantile",
    "traces_response_payload",
]

# Process-wide default tracer: components without an injected tracer
# (Trainer, ad-hoc scripts) share it, so one /debug/traces view can
# correlate them.
DEFAULT_TRACER = Tracer()

_default_registry = None


def default_registry():
    """Lazy process-wide Registry (see import discipline above)."""
    global _default_registry
    if _default_registry is None:
        from kubeflow_tpu.controlplane.metrics import Registry

        _default_registry = Registry()
    return _default_registry
