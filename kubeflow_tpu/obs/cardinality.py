"""Label-cardinality guard for the metrics layer.

Per-tenant labels are bounded by tenant CONFIG on the serving side
(unknown identities resolve to `default` before they reach a metric),
but anywhere a raw client-supplied value becomes a label — the router's
per-tenant counters, span attributes echoing an `X-Tenant` header — an
attacker sending a fresh value per request would mint a fresh
timeseries per request. `LabelGuard` caps the distinct values a label
may take: known (seeded) values pass through, novel values pass until
the cap, and everything past the cap collapses into one overflow
bucket (`other`)."""

from __future__ import annotations

import hashlib
import threading

OVERFLOW_LABEL = "other"


class LabelGuard:
    """Bounded admission of label values. Thread-safe: counters are
    bumped from handler threads and rendered from scrape time."""

    def __init__(self, max_values: int = 32,
                 overflow: str = OVERFLOW_LABEL, seed=(),
                 closed: bool = False, hashed: bool = False):
        if max_values < 1:
            raise ValueError(f"max_values must be >= 1, got {max_values}")
        self.max_values = int(max_values)
        self.overflow = overflow
        # closed guards admit ONLY the seeded set — the right mode for
        # label values that enumerate code (phase names, watched fn
        # names), where a novel value is a bug, not a new tenant
        self.closed = bool(closed)
        # hashed guards never grow state at all: admit() maps every
        # value to 16 hex chars of blake2b, so the FORMAT is bounded by
        # construction and the VALUE never leaks raw client data into a
        # label. The series count is bounded by the caller (e.g. a
        # top-K prefix-heat digest), not by this guard — there is no
        # overflow bucket and nothing to seed.
        self.hashed = bool(hashed)
        if self.hashed and self.closed:
            raise ValueError("hashed and closed modes are exclusive")
        self._lock = threading.Lock()
        self._values: set[str] = set()
        self.overflowed = 0  # values that hit the cap, cumulative
        for v in seed:
            with self._lock:
                self._values.add(v or self.overflow)

    def admit(self, value: str) -> str:
        """The label value to actually use for `value`: itself while
        seeded (closed mode) or under the cap (open mode), the overflow
        bucket after; hashed mode returns the 16-hex digest of any
        value. The overflow bucket itself never counts against the
        cap."""
        value = value or self.overflow
        if self.hashed:
            return hashlib.blake2b(
                value.encode("utf-8", "replace"),
                digest_size=8).hexdigest()
        if value == self.overflow:
            return self.overflow
        with self._lock:
            if value in self._values:
                return value
            if not self.closed and len(self._values) < self.max_values:
                self._values.add(value)
                return value
            self.overflowed += 1
            return self.overflow

    def known(self) -> set[str]:
        with self._lock:
            return set(self._values)
