"""Step-anatomy profiling: phase attribution, goodput, compile-watch.

Metrics say HOW LONG a step took; traces say WHICH step was slow. This
module answers WHERE the time went: every `ContinuousBatcher` worker
iteration (and every `Trainer.step`) decomposes into named phases with
per-phase wall time, token counts, and occupancy, aggregated three
ways —

  1. `serving_step_phase_seconds{phase}` / `serving_step_tokens{phase}`
     histograms (the server binds them through `on_phase`, zero-seeded
     so dashboards see every phase from the first scrape),
  2. a goodput ledger: decode device-time over total non-idle step
     time, bubble fraction (host-gap share), and occupancy / KV-pool
     high-water marks,
  3. Chrome-trace COUNTER tracks (`"ph": "C"`) merged into the same
     `/debug/traces` payload as the span events, so one trace shows
     phase budgets and pool fill over time next to the spans.

Phase mapping for the continuous batcher (the honest one for this
architecture — sampling is fused into the device step, so the host-side
phases measure what the HOST does around it):

  admit       queue pop, block planning, grouping, insert dispatch
  prefill     the grouped prefill/gather device call
  decode      decode-chunk dispatch + waiting on device results
  sample      host materialization of sampled tokens (device->numpy)
  detokenize  per-token emit bookkeeping (stop-seq scan, timelines,
              stream queues)
  preempt     evicting a batch decode (cache blocks, release slot)
  resume      zero-duration marker per preemption replay admission
  host_gap    the iteration residual no explicit phase claims — the
              bubble dispatch-ahead exists to hide
  idle        waiting for work (empty batcher); excluded from goodput

Phase and fn label values are CLOSED SETS behind `LabelGuard`s: an
unknown name collapses to `other` instead of minting a series.

The compile-watch wraps jitted callables and keys every call by the
ABSTRACT signature of its arguments (shape/dtype for arrays, value for
python scalars — matching `static_argnames` semantics for the wrapped
fns here, whose only scalar args are static). A signature never seen
before, beyond the fn's first (the expected initial compile), is a
retrace: the counter hook fires (`serving_recompiles_total{fn}` /
`train_recompiles_total{fn}`) and a `recompile` span records the
offending signature. Steady-state decode repeats one signature, so a
nonzero rate is always news.

No jax import here: obs stays importable in jax-free processes, and
signatures duck-type ``.shape``/``.dtype`` instead of tracing.
"""

from __future__ import annotations

import collections
import contextlib
import functools
import threading
import time
from typing import Any, Callable

from kubeflow_tpu.obs.cardinality import LabelGuard
from kubeflow_tpu.obs.metrics import sample_quantile

# The serving step anatomy (ContinuousBatcher worker loop).
# prefill_chunk = chunked-prefill slices interleaved with decode
# (ISSUE 9); draft/verify = the speculative round's two device legs.
SERVING_PHASES = ("admit", "prefill", "prefill_chunk", "decode",
                  "draft", "verify", "sample", "detokenize",
                  "preempt", "resume", "host_gap", "idle")
# The training step anatomy (Trainer.step): one device phase plus the
# host gap between consecutive steps (input pipeline, checkpointing).
TRAIN_PHASES = ("step", "host_gap")
# Goodput numerator per anatomy: the phase that is useful device work
# (draft/verify are the speculative round's token-producing legs).
GOODPUT_PHASES = ("decode", "draft", "verify", "step")
# Phases excluded from the goodput denominator: an empty batcher
# parked on its wake event is not a bubble, it has no work.
IDLE_PHASES = ("idle",)

# Jitted callables the serving compile-watch wraps (closed fn set).
WATCHED_SERVING_FNS = ("decode_step", "prefill", "insert_many",
                       "gather_seed", "reset_slots", "prefill_append",
                       "spec_draft", "spec_verify")
WATCHED_TRAIN_FNS = ("train_step",)

_MAX_COUNTER_EVENTS = 2048


def abstract_signature(args: tuple, kwargs: dict) -> str:
    """Compact hashable key for a call's abstract shapes: arrays render
    as `dtype[d0,d1,...]` (duck-typed — works for jax/numpy arrays and
    ShapeDtypeStructs without importing either), python scalars by
    value (static-arg semantics), containers structurally."""

    def sig(x: Any) -> str:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            return f"{dtype}[{','.join(str(d) for d in shape)}]"
        if isinstance(x, (bool, int, float, str, bytes)) or x is None:
            return repr(x)
        if isinstance(x, (tuple, list)):
            return "(" + ",".join(sig(v) for v in x) + ")"
        if isinstance(x, dict):
            items = sorted(x.items(), key=lambda kv: str(kv[0]))
            return "{" + ",".join(f"{k}:{sig(v)}" for k, v in items) + "}"
        # opaque leaves (pytree nodes the duck-typing missed) key by
        # TYPE only: better to miss a retrace than to invent one per
        # object identity
        return type(x).__name__

    return sig(args) + sig(kwargs) if kwargs else sig(args)


class _PhaseStats:
    __slots__ = ("count", "total_s", "tokens", "window")

    def __init__(self, window: int | None):
        self.count = 0
        self.total_s = 0.0
        self.tokens = 0
        self.window: Any = (collections.deque(maxlen=window)
                            if window else [])


class PhaseProfiler:
    """Aggregates named-phase timings into totals, rolling-window
    percentiles, a goodput ledger, and Chrome counter tracks.

    Usage (the batcher/trainer side):

        with profiler.phase("decode", tokens=steps * occupancy):
            ... device call ...

    Phases nest: a parent's recorded duration EXCLUDES time spent in
    nested phases (admit excludes the prefill dispatch it contains), so
    phase sums reconcile against wall time without double counting.
    `begin_iteration`/`end_iteration` bracket one worker-loop pass and
    book the unclaimed residual as `host_gap` — by construction the
    phase sums then equal the measured loop wall time.

    Everything here is defensive pure python: a profiler bug must never
    kill the instrumented worker, so the `on_phase` hook is swallowed
    like every other batcher hook and internal state is lock-guarded.
    """

    def __init__(self, *, phases: tuple[str, ...] = SERVING_PHASES,
                 clock: Callable[[], float] | None = None,
                 wall_clock: Callable[[], float] | None = None,
                 window: int | None = 512):
        self.phases = tuple(phases)
        self.guard = LabelGuard(seed=self.phases, closed=True)
        self._clock = clock or time.perf_counter
        self._wall = wall_clock or time.time
        self._window = window
        self._lock = threading.Lock()
        self._stats: dict[str, _PhaseStats] = {
            p: _PhaseStats(window) for p in self.phases}
        # nesting stack (single worker task/thread by construction):
        # [name, start, child_seconds]
        self._stack: list[list] = []
        self._iter_t0: float | None = None
        self._iter_claimed = 0.0
        self._t_first: float | None = None
        self._t_last: float | None = None
        # optional hook(phase, seconds, tokens) — the server wires the
        # labeled histograms through it; exceptions are swallowed.
        # seconds is None for token-only attributions (add_tokens).
        self.on_phase: Callable[[str, float | None, int], None] | None \
            = None
        # goodput ledger extras
        self.pool_high_water = 0
        self.pool_capacity = 0
        self.occupancy_high_water = 0
        self.slots = 0
        self._pool_last = -1
        self._occ_last = -1
        self._events: collections.deque = collections.deque(
            maxlen=_MAX_COUNTER_EVENTS)

    # -- recording ---------------------------------------------------------

    @contextlib.contextmanager
    def phase(self, name: str, tokens: int = 0):
        start = self._clock()
        if self._t_first is None:
            # the observed-wall window opens at the first phase START
            # (record() only back-dates by the EXCLUSIVE duration, which
            # undercounts when the first record is a nested child)
            self._t_first = start
        frame = [name, start, 0.0]
        self._stack.append(frame)
        try:
            yield
        finally:
            dur = self._clock() - start
            if self._stack and self._stack[-1] is frame:
                self._stack.pop()
            if self._stack:
                self._stack[-1][2] += dur
            self.record(name, max(0.0, dur - frame[2]), tokens=tokens)

    def record(self, name: str, seconds: float, tokens: int = 0) -> None:
        name = self.guard.admit(name)
        seconds = max(0.0, float(seconds))
        now = self._clock()
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _PhaseStats(self._window)
            st.count += 1
            st.total_s += seconds
            st.tokens += int(tokens)
            st.window.append(seconds)
            self._t_last = now
            if self._t_first is None:
                self._t_first = now - seconds
            if self._iter_t0 is not None:
                # phases record EXCLUSIVE durations (nesting subtracts
                # child time), so summing every record — nested or
                # not — claims exactly the inclusive wall of the
                # iteration's top-level phases
                self._iter_claimed += seconds
        if self.on_phase is not None:
            try:
                self.on_phase(name, seconds, int(tokens))
            except Exception:  # noqa: BLE001 — metrics hook
                pass           # must never kill the instrumented loop

    def add_tokens(self, name: str, tokens: int) -> None:
        """Attribute tokens to a phase without a timing sample (decode
        tokens are counted where they are OBSERVED — at host
        processing — while decode time is measured at dispatch)."""
        if tokens <= 0:
            return
        name = self.guard.admit(name)
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = self._stats[name] = _PhaseStats(self._window)
            st.tokens += int(tokens)
        if self.on_phase is not None:
            try:
                self.on_phase(name, None, int(tokens))
            except Exception:  # noqa: BLE001 — metrics hook
                pass

    def begin_iteration(self) -> None:
        self._iter_t0 = self._clock()
        self._iter_claimed = 0.0

    def end_iteration(self) -> None:
        """Book the loop-pass residual (wall minus every top-level
        phase recorded since begin_iteration) as `host_gap` — the
        attribution invariant `sum(phases) == loop wall` holds by
        construction."""
        if self._iter_t0 is None:
            return
        residual = (self._clock() - self._iter_t0) - self._iter_claimed
        self._iter_t0 = None
        if residual > 0.0:
            self.record("host_gap", residual)
        self._emit_phase_track()

    # -- pool / occupancy high-water marks ---------------------------------

    def note_pool(self, in_use: int, capacity: int) -> None:
        self.pool_capacity = int(capacity)
        in_use = int(in_use)
        if in_use > self.pool_high_water:
            self.pool_high_water = in_use
        if in_use != self._pool_last:
            self._pool_last = in_use
            self._events.append({
                "name": "kv_blocks", "ph": "C",
                "ts": round(self._wall() * 1e6, 1), "pid": 1, "tid": 0,
                "args": {"in_use": in_use}})

    def note_occupancy(self, occupied: int, slots: int) -> None:
        self.slots = int(slots)
        occupied = int(occupied)
        if occupied > self.occupancy_high_water:
            self.occupancy_high_water = occupied
        if occupied != self._occ_last:
            self._occ_last = occupied
            self._events.append({
                "name": "batch_occupancy", "ph": "C",
                "ts": round(self._wall() * 1e6, 1), "pid": 1, "tid": 0,
                "args": {"slots_active": occupied}})

    def _emit_phase_track(self) -> None:
        with self._lock:
            args = {p: round(st.total_s, 6)
                    for p, st in self._stats.items() if st.count}
        if args:
            self._events.append({
                "name": "phase_seconds", "ph": "C",
                "ts": round(self._wall() * 1e6, 1), "pid": 1, "tid": 0,
                "args": args})

    # -- read side ---------------------------------------------------------

    def counter_events(self, *, prefix: str = "") -> list[dict]:
        """Chrome counter-track events (`"ph": "C"`), timestamped on
        the same wall clock as the tracer's span events so they merge
        into one `/debug/traces` payload. `prefix` namespaces the track
        names per model."""
        out = []
        for e in list(self._events):
            e = dict(e)
            if prefix:
                e["name"] = f"{prefix}.{e['name']}"
            out.append(e)
        return out

    def totals(self) -> dict[str, float]:
        with self._lock:
            return {p: st.total_s for p, st in self._stats.items()}

    def phase_tokens(self) -> dict[str, int]:
        with self._lock:
            return {p: st.tokens for p, st in self._stats.items()}

    def samples(self, name: str) -> list[float]:
        with self._lock:
            st = self._stats.get(name)
            return list(st.window) if st else []

    def wall_s(self) -> float:
        """Wall window the profiler has observed (first record to
        last) — what the attribution 5%-reconciliation compares phase
        sums against."""
        with self._lock:
            if self._t_first is None or self._t_last is None:
                return 0.0
            return self._t_last - self._t_first

    def goodput(self) -> dict[str, float]:
        """The ledger: useful-device-time share of non-idle wall, the
        bubble (host_gap) share, and the high-water marks."""
        with self._lock:
            totals = {p: st.total_s for p, st in self._stats.items()}
        busy = sum(s for p, s in totals.items() if p not in IDLE_PHASES)
        good = sum(totals.get(p, 0.0) for p in GOODPUT_PHASES)
        bubble = totals.get("host_gap", 0.0)
        return {
            "goodput_ratio": good / busy if busy > 0 else 0.0,
            "bubble_fraction": bubble / busy if busy > 0 else 0.0,
            "busy_s": busy,
            "idle_s": sum(totals.get(p, 0.0) for p in IDLE_PHASES),
            "kv_blocks_high_water": self.pool_high_water,
            "kv_blocks_capacity": self.pool_capacity,
            "occupancy_high_water": self.occupancy_high_water,
            "slots": self.slots,
        }

    def snapshot(self) -> dict:
        """The `/debug/profile` building block: per-phase counts,
        totals, tokens, and rolling p50/p95 (same interpolation as
        `Histogram.quantile` — see `sample_quantile`), plus the goodput
        ledger."""
        phases = {}
        with self._lock:
            items = [(p, st.count, st.total_s, st.tokens,
                      list(st.window)) for p, st in self._stats.items()]
        for p, count, total_s, tokens, win in items:
            phases[p] = {
                "count": count,
                "total_s": round(total_s, 6),
                "tokens": tokens,
                "p50_s": sample_quantile(win, 0.50),
                "p95_s": sample_quantile(win, 0.95),
            }
        return {"phases": phases, "goodput": self.goodput(),
                "wall_s": round(self.wall_s(), 6)}


class CompileWatch:
    """Retrace detector over jitted callables.

    `watch(fn, name)` returns a wrapper that keys every call by
    `abstract_signature(args, kwargs)`. The FIRST signature per fn is
    the expected initial compile; every novel signature after it is a
    retrace: the local ledger increments, `on_recompile(fn, sig)` fires
    (the server binds the `*_recompiles_total{fn}` counter there), and
    when a tracer is attached a `recompile` span records the offending
    signature. Calls repeating a seen signature cost one string build
    and a set lookup.

    fn names are a closed set behind a LabelGuard (seeded by `watch`),
    so the label space cannot grow past the wrapped callables.
    """

    def __init__(self, *, tracer=None,
                 on_recompile: Callable[[str, str], None] | None = None):
        self.tracer = tracer
        self.on_recompile = on_recompile
        self.guard = LabelGuard()
        self._seen: dict[str, set[str]] = {}
        self._recompiles: dict[str, int] = {}
        self._lock = threading.Lock()

    def watch(self, fn: Callable, name: str) -> Callable:
        name = self.guard.admit(name)
        with self._lock:
            self._seen.setdefault(name, set())
            self._recompiles.setdefault(name, 0)

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            try:
                sig = abstract_signature(args, kwargs)
            except Exception:  # noqa: BLE001 — watch must not break fn
                return fn(*args, **kwargs)
            with self._lock:
                seen = self._seen[name]
                novel = sig not in seen
                first = novel and not seen
                if novel:
                    seen.add(sig)
                    if not first:
                        self._recompiles[name] += 1
            if novel and not first:
                self._note_recompile(name, sig)
            return fn(*args, **kwargs)

        return wrapped

    def _note_recompile(self, name: str, sig: str) -> None:
        if self.tracer is not None:
            try:
                with self.tracer.span("recompile", fn=name,
                                      signature=sig[:512]):
                    pass
            except Exception:  # noqa: BLE001
                pass
        if self.on_recompile is not None:
            try:
                self.on_recompile(name, sig)
            except Exception:  # noqa: BLE001 — metrics hook
                pass

    def counts(self) -> dict[str, int]:
        """Per-fn retrace counts (the `/debug/profile` `recompiles`
        block; mirrors the `*_recompiles_total{fn}` counters)."""
        with self._lock:
            return dict(self._recompiles)

    def watched(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(self._seen)


def merge_counter_tracks(payload: dict, events: list[dict]) -> dict:
    """Append counter-track events to a Chrome-trace payload in place
    (no-op for summary payloads without `traceEvents`)."""
    if isinstance(payload, dict) and isinstance(
            payload.get("traceEvents"), list):
        payload["traceEvents"].extend(events)
    return payload
