"""In-process span tracer: contextvar parent propagation, bounded trace
ring, Chrome-trace JSON export.

The platform's cross-layer latency story (ISSUE 1): reconcile loops,
serving requests and train steps all open spans through one Tracer, so
`/debug/traces` can show a serving request's child spans next to the
reconcile that scheduled its pod. No OpenTelemetry dependency — traces
stay in a process-local ring and export as Chrome trace events
(`chrome://tracing` / Perfetto load them directly); the XLA profiler
(utils/profiling.py) remains the inside-the-step microscope, these
spans are the between-steps map.

Propagation is `contextvars`, so spans nest correctly across asyncio
tasks (each request handler is its own context) and plain call stacks.
A span opened with no current parent starts a new trace; finishing a
root span commits the whole trace to the ring (oldest trace evicted
first).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import secrets
import threading
import time
from typing import Any, Callable, Iterator

# Spans per trace are bounded too: a runaway loop opening child spans
# must not grow one trace without limit while it stays unfinished.
MAX_SPANS_PER_TRACE = 512


def _valid_span_id(raw: str) -> bool:
    """Remote trace/span ids arrive in HTTP headers; accept only what
    `secrets.token_hex` could have minted (lowercase hex, sane length)
    so a hostile header cannot smuggle junk into trace exports."""
    return (isinstance(raw, str) and 8 <= len(raw) <= 64
            and all(c in "0123456789abcdef" for c in raw))


class Span:
    """One timed operation. `start`/`end` are epoch seconds."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end", "attrs", "thread", "_trace")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str | None, start: float,
                 attrs: dict[str, Any], trace: "_Trace"):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end: float | None = None
        self.attrs = attrs
        self.thread = threading.get_ident()
        self._trace = trace

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else self.start) - self.start

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "start": self.start,
            "durationMs": round(self.duration * 1e3, 3),
            "attrs": dict(self.attrs),
        }


class _Trace:
    """Finished-span collector for one trace id (root + descendants)."""

    __slots__ = ("trace_id", "spans", "root", "seq")

    def __init__(self, trace_id: str, seq: int):
        self.trace_id = trace_id
        self.spans: list[Span] = []
        self.root: Span | None = None
        self.seq = seq  # monotonic commit order (newest-first sorting)

    def add(self, span: Span) -> None:
        if len(self.spans) < MAX_SPANS_PER_TRACE:
            self.spans.append(span)


class Tracer:
    """`with tracer.span("name", key=value): ...`

    Thread-safe; each Tracer owns its ring so tests and independently
    deployed apps stay isolated. `max_traces` bounds memory — the ring
    evicts the OLDEST finished trace first.
    """

    def __init__(self, max_traces: int = 256,
                 clock: Callable[[], float] | None = None):
        import collections

        self.max_traces = max_traces
        self._clock = clock or time.time
        self._traces: "collections.deque[_Trace]" = collections.deque(
            maxlen=max_traces)
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._current: contextvars.ContextVar[Span | None] = \
            contextvars.ContextVar(f"obs_span_{id(self)}", default=None)

    # -- span lifecycle ----------------------------------------------------

    def current_span(self) -> Span | None:
        return self._current.get()

    def current_trace_id(self) -> str | None:
        s = self._current.get()
        return s.trace_id if s is not None else None

    @contextlib.contextmanager
    def span(self, name: str, /, **attrs: Any) -> Iterator[Span]:
        # positional-only `name`: attrs are arbitrary key=value pairs
        # and "name" is a natural attr key (reconcile object names).
        parent = self._current.get()
        if parent is None:
            trace = _Trace(secrets.token_hex(16), next(self._seq))
            trace_id, parent_id = trace.trace_id, None
        else:
            trace = parent._trace
            trace_id, parent_id = parent.trace_id, parent.span_id
        s = Span(name, trace_id, secrets.token_hex(8), parent_id,
                 self._clock(), dict(attrs), trace)
        token = self._current.set(s)
        try:
            yield s
        except BaseException as e:
            s.attrs.setdefault("error", type(e).__name__)
            raise
        finally:
            s.end = self._clock()
            self._current.reset(token)
            trace.add(s)
            if parent is None:
                trace.root = s
                with self._lock:
                    self._traces.append(trace)

    @contextlib.contextmanager
    def span_from_remote(self, name: str, trace_id: str,
                         parent_span_id: str, /,
                         **attrs: Any) -> Iterator[Span]:
        """Open a root span that ADOPTS a remote parent context — the
        receiving half of cross-process propagation (`X-Trace-Id` +
        `X-Parent-Span` injected by the fleet router). The local trace
        commits under the REMOTE trace id with the remote span as
        parent, so both processes' rings hold joinable segments of one
        logical trace and a merger can reassemble the full tree.

        Malformed ids (propagation is an open HTTP header — never
        trust it) or an already-open local parent fall back to a
        normal `span()`: a bad header must not corrupt local nesting.
        """
        if (self._current.get() is not None
                or not _valid_span_id(trace_id)
                or not _valid_span_id(parent_span_id)):
            with self.span(name, **attrs) as s:
                yield s
            return
        trace = _Trace(trace_id, next(self._seq))
        s = Span(name, trace_id, secrets.token_hex(8), parent_span_id,
                 self._clock(), dict(attrs), trace)
        token = self._current.set(s)
        try:
            yield s
        except BaseException as e:
            s.attrs.setdefault("error", type(e).__name__)
            raise
        finally:
            s.end = self._clock()
            self._current.reset(token)
            trace.add(s)
            # This span is the local root: it commits the trace even
            # though its parent_id points at the remote caller's span.
            trace.root = s
            with self._lock:
                self._traces.append(trace)

    def wrap(self, fn: Callable, name: str, /, **attrs: Any) -> Callable:
        """Propagate the CURRENT context into a thread-pool callable
        (run_in_executor does not copy contextvars): the returned
        closure re-enters this context and opens `name` inside it, so
        device work dispatched to an executor still nests under the
        request's root span."""
        ctx = contextvars.copy_context()

        def run(*args, **kwargs):
            def inner():
                with self.span(name, **attrs):
                    return fn(*args, **kwargs)

            return ctx.run(inner)

        return run

    # -- read side ---------------------------------------------------------

    def traces(self, name: str | None = None,
               limit: int | None = None,
               trace_id: str | None = None) -> list[dict[str, Any]]:
        """Finished traces, NEWEST first, optionally filtered by root
        span name and/or exact trace id. Each entry: trace summary +
        its spans."""
        with self._lock:
            snap = list(self._traces)
        snap.sort(key=lambda t: t.seq, reverse=True)
        out = []
        for t in snap:
            root = t.root
            if root is None:
                continue
            if name is not None and root.name != name:
                continue
            if trace_id is not None and t.trace_id != trace_id:
                continue
            out.append({
                "traceId": t.trace_id,
                "name": root.name,
                "start": root.start,
                "durationMs": round(root.duration * 1e3, 3),
                "spans": [s.to_dict() for s in t.spans],
            })
            if limit is not None and len(out) >= limit:
                break
        return out

    def chrome_trace(self, name: str | None = None,
                     limit: int | None = None,
                     trace_id: str | None = None) -> dict[str, Any]:
        """Chrome trace-event JSON (the `chrome://tracing` / Perfetto
        load format): one complete ("ph": "X") event per span, ts/dur
        in microseconds, traces ordered newest first. `args` carries
        the span attrs plus trace/span ids so events remain joinable
        back to `X-Trace-Id` response headers."""
        events = []
        for t in self.traces(name=name, limit=limit, trace_id=trace_id):
            for s in t["spans"]:
                events.append({
                    "name": s["name"],
                    "cat": "obs",
                    "ph": "X",
                    "ts": round(s["start"] * 1e6, 1),
                    "dur": round(s["durationMs"] * 1e3, 1),
                    "pid": 1,
                    "tid": 1,
                    "args": {
                        "trace_id": s["traceId"],
                        "span_id": s["spanId"],
                        "parent_id": s["parentId"],
                        **s["attrs"],
                    },
                })
        return {"displayTimeUnit": "ms", "traceEvents": events}


def traces_response_payload(tracer: Tracer, query) -> dict[str, Any]:
    """Shared `/debug/traces` handler body for the dashboard and
    serving apps: `?name=` filters by root span name, `?limit=` caps
    trace count (default 100), `?format=summary` returns the span-tree
    summaries instead of Chrome events, `?trace_id=` selects one
    trace exactly (the id from an `X-Trace-Id` response header)."""
    name = query.get("name") or None
    trace_id = query.get("trace_id") or None
    try:
        limit = int(query.get("limit", "100"))
    except ValueError as e:
        raise ValueError(f"limit must be an integer: {e}") from None
    if query.get("format") == "summary":
        return {"traces": tracer.traces(name=name, limit=limit,
                                        trace_id=trace_id)}
    return tracer.chrome_trace(name=name, limit=limit, trace_id=trace_id)


def merge_chrome_traces(
        segments: list[tuple[str, dict[str, Any]]]) -> dict[str, Any]:
    """Merge per-process Chrome-trace payloads into one document — the
    cross-process half of distributed tracing. Each segment gets its
    own `pid` plus a `process_name` metadata event, so Perfetto shows
    "router" and each replica as separate process tracks while spans
    stay joinable through the shared `args.trace_id`/`parent_id`."""
    events: list[dict[str, Any]] = []
    for pid, (source, payload) in enumerate(segments, start=1):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 1, "args": {"name": source}})
        for e in payload.get("traceEvents", []):
            if e.get("ph") == "M":
                continue  # sources' own metadata is superseded
            events.append({**e, "pid": pid})
    return {"displayTimeUnit": "ms", "traceEvents": events}
