"""KV-cache block lifecycle ledger + bounded prefix hashing.

The paged-KV pool (`serving/paged.py`) is the resource that actually
caps a replica's concurrency, and until now its observability stopped
at "blocks in use" plus one aggregate hit/miss pair. This module adds
the accounting the fleet-wide cache-tier work needs:

- `CacheLedger`: a pure-python sidecar the `BlockPool` notifies on
  every block birth and death. Every death is booked to a CAUSE from a
  closed set (`EVICTION_CAUSES`); a `pool.free()` call that forgot to
  say why lands in `unattributed`, which CI asserts is zero — the same
  structural-conservation discipline as PR 8's phase-sums == wall.
  The ledger also keeps reuse distances (admissions between touches of
  the same block), block age at death, and admission-defer causes.
- `prefix_hash`: the ONE hash both replicas and the router use to name
  a prefix (first KV block of tokens). 16 hex chars of blake2b, salted
  by tenant namespace, so per-prefix label cardinality is bounded by
  construction (fixed format, top-K digests only) and a replica's heat
  digest can be joined against the router's routing key without ever
  shipping raw prompt tokens off the replica.

The ledger is metric-free (importable in jax-only processes); the
serving layer binds its `on_*` hooks to real counters/histograms, the
same wiring idiom as `PhaseProfiler.on_phase`.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from typing import Callable, Iterable

from .metrics import sample_quantile

# Closed set of reasons a KV block dies. These become the `cause` label
# on `serving_kv_evictions_total`, so the set is CLOSED by design:
#   lru        — radix prefix-cache LRU eviction (cold prefix displaced)
#   pressure   — slot preemption under pool pressure (victim's blocks)
#   refdrop    — normal retirement: request finished/cancelled/failed
#                and its non-cached blocks dropped their last reference
#   divergence — copy-on-write/import duplicate: a block whose contents
#                already exist under another id (freed immediately)
#   migration  — blocks handed to / rolled back from a peer replica
#   spill      — LRU victim DEMOTED to the host-RAM spill tier instead
#                of discarded: the device block is freed but the
#                content survives on the host (restorable)
EVICTION_CAUSES = ("lru", "pressure", "refdrop", "divergence",
                   "migration", "spill")
# Where a `pool.free()` with no stated cause is booked. Conservation CI
# asserts this series stays at zero — it existing (zero-seeded) is what
# makes "every free site states its cause" checkable from /metrics.
UNATTRIBUTED = "unattributed"
# Why an admission was deferred this step (`serving_kv_admission_defers_
# _total{cause}`): per-tenant KV quota vs the pool simply being empty
# even after LRU eviction.
DEFER_CAUSES = ("kv_quota", "pool_exhausted")
# Where an admitted prompt's tokens came from
# (`serving_prefill_tokens{source}` — CLOSED set, zero-seeded):
#   computed     — suffix actually prefilled on the device
#   reused       — served from device-resident cached KV (radix hit)
#   restored     — promoted from the host-RAM spill tier (host->device
#                  copy; a radix hit whose content had been demoted)
#   peer_fetched — imported from a peer replica's cache via the
#                  router's X-KV-Peer heat hint
PREFILL_SOURCES = ("computed", "reused", "restored", "peer_fetched")
# Outcome of one replica-side peer block fetch
# (`fleet_peer_fetch_total{outcome}` — CLOSED set, zero-seeded). Only
# `ok` imported blocks; miss/failed degraded to plain prefill.
PEER_FETCH_OUTCOMES = ("ok", "miss", "failed")

# Reuse-distance / block-age buckets, in ADMISSIONS (logical ticks, one
# per admitted request) — powers of two out past any realistic pool
# residency. Distance ~pool-size is the working-set cliff: blocks whose
# reuse distance exceeds the pool's capacity in blocks will have been
# evicted before their next use.
REUSE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0, 4096.0)

# Raw-sample windows for the /debug/profile quantiles (the histograms
# keep the unbounded cumulative view; these keep the recent shape).
_WINDOW = 512
_MAX_COUNTER_EVENTS = 2048


def canonical_prefix(tokens: Iterable[int], ns: str = "") -> str:
    """Canonical string form of a token prefix: space-joined decimal
    ints (the router's rendezvous `affinity_key` form), NUL-salted by
    tenant namespace when namespaced. This is the string a hashed
    `LabelGuard` digests — replica heat digests and the router's
    routing key MUST hash the same canonical form or the fleet heat
    map joins garbage."""
    joined = " ".join(str(int(t)) for t in tokens)
    return f"{ns}\x00{joined}" if ns else joined


def prefix_hash(tokens: Iterable[int], ns: str = "") -> str:
    """16-hex name for a token prefix, salted by tenant namespace —
    blake2b-64 of `canonical_prefix`, byte-identical to what a hashed
    LabelGuard returns for the same canonical string."""
    return hashlib.blake2b(
        canonical_prefix(tokens, ns).encode("utf-8", "replace"),
        digest_size=8).hexdigest()


class CacheLedger:
    """Block lifecycle accounting for one BlockPool.

    Attach by assigning to `pool.ledger`; the pool then calls
    `note_alloc` / `note_free` inline (pure dict/deque work, no metric
    or lock-ordering hazards on the hot path beyond one short lock).
    The batcher calls `note_admission` once per admitted request (the
    logical clock), `note_reuse` for radix-hit blocks, and `note_defer`
    when admission is pushed back.

    Conservation invariant (asserted by tests and `ci/obs_check cache`):
        births - sum(frees over all causes) == pool.in_use
    and `frees[UNATTRIBUTED] == 0` — every free site states its cause.

    With a host-RAM spill tier attached (PR 19) the ledger also books
    the CONTENT lifecycle: a `spill` free demotes a block's content to
    the host tier (`spilled` += 1), `note_restore` moves it back into
    a freshly-allocated device block (the alloc's birth is a re-birth,
    not new content), and `note_spill_drop` books host-tier budget
    evictions (content truly dead). The extended conservation — the
    ISSUE-19 shorthand `births − frees == live + spilled` — is then
        (births - restores) - (frees_total - frees["spill"] + drops)
            == live_blocks + spilled
    i.e. content born minus content dead equals content reachable on
    device plus content parked on the host. Both equalities must hold
    for `snapshot()["conserved"]`.
    """

    def __init__(self, *, window: int = _WINDOW,
                 wall: Callable[[], float] = time.time):
        self._lock = threading.Lock()
        self._wall = wall
        self._tick = 0                       # admissions so far
        self.births = 0
        self.frees = {c: 0 for c in (*EVICTION_CAUSES, UNATTRIBUTED)}
        self.defers = {c: 0 for c in DEFER_CAUSES}
        # Host-RAM spill tier accounting (PR 19). `spilled` counts
        # block contents currently parked on the host; demotions /
        # restores / drops are the cumulative transitions in and out.
        self.spilled = 0
        self.spill_demotions = 0
        self.spill_restores = 0
        self.spill_drops = 0
        # live block id -> (birth_tick, last_use_tick)
        self._live: dict[int, list[int]] = {}
        self._reuse = deque(maxlen=window)   # distances, in admissions
        self._ages = deque(maxlen=window)    # age at death, admissions
        # Chrome "C" counter events: one all-zero seed so the track
        # exists in every trace, then one point per free.
        self._events: deque = deque(maxlen=_MAX_COUNTER_EVENTS)
        self._emit_event()
        # serving-layer metric bindings; exceptions are swallowed so a
        # bad hook can never kill the batcher worker (PhaseProfiler
        # idiom)
        self.on_free: Callable[[str, int], None] | None = None
        self.on_reuse: Callable[[int], None] | None = None
        self.on_age: Callable[[int], None] | None = None
        self.on_defer: Callable[[str], None] | None = None
        # on_spill(kind, n) with kind in {"demote", "restore", "drop"}
        # — the server binds the spill counters through this
        self.on_spill: Callable[[str, int], None] | None = None

    # -- pool-side hooks ---------------------------------------------------

    def note_alloc(self, blocks: Iterable[int]) -> None:
        with self._lock:
            t = self._tick
            for b in blocks:
                self._live[int(b)] = [t, t]
                self.births += 1

    def note_free(self, blocks: Iterable[int], cause: str | None) -> None:
        cause = cause if cause in self.frees else UNATTRIBUTED
        ages = []
        with self._lock:
            n = 0
            for b in blocks:
                n += 1
                meta = self._live.pop(int(b), None)
                if meta is not None:
                    age = self._tick - meta[0]
                    self._ages.append(age)
                    ages.append(age)
            if n:
                self.frees[cause] += n
                if cause == "spill":
                    # the device block died but its content moved to
                    # the host tier — the content-conservation books
                    self.spill_demotions += n
                    self.spilled += n
                self._emit_event()
        if n and self.on_free is not None:
            try:
                self.on_free(cause, n)
            except Exception:
                pass
        if n and cause == "spill" and self.on_spill is not None:
            try:
                self.on_spill("demote", n)
            except Exception:
                pass
        if self.on_age is not None:
            for age in ages:
                try:
                    self.on_age(age)
                except Exception:
                    pass

    # -- batcher-side hooks ------------------------------------------------

    def note_admission(self) -> None:
        """Advance the logical clock: one tick per admitted request."""
        with self._lock:
            self._tick += 1

    def note_reuse(self, blocks: Iterable[int]) -> None:
        """Radix-hit blocks for the request being admitted: records the
        reuse distance (admissions since each block's last touch)."""
        dists = []
        with self._lock:
            t = self._tick
            for b in blocks:
                meta = self._live.get(int(b))
                if meta is None:
                    continue
                d = t - meta[1]
                dists.append(d)
                self._reuse.append(d)
                meta[1] = t
        if self.on_reuse is not None:
            for d in dists:
                try:
                    self.on_reuse(d)
                except Exception:
                    pass

    def note_restore(self, n: int) -> None:
        """`n` spilled block contents copied back into freshly
        allocated device blocks. The allocs already booked their
        births via `note_alloc`; this books the host-tier exits so
        the content-conservation equality nets the re-births out."""
        n = int(n)
        if n <= 0:
            return
        with self._lock:
            self.spill_restores += n
            self.spilled -= n
        if self.on_spill is not None:
            try:
                self.on_spill("restore", n)
            except Exception:
                pass

    def note_spill_drop(self, n: int) -> None:
        """`n` host-tier entries evicted by the tier's byte budget (or
        lost to a failed restore): the content is truly dead now."""
        n = int(n)
        if n <= 0:
            return
        with self._lock:
            self.spill_drops += n
            self.spilled -= n
        if self.on_spill is not None:
            try:
                self.on_spill("drop", n)
            except Exception:
                pass

    def note_defer(self, cause: str) -> None:
        if cause not in self.defers:
            cause = "pool_exhausted"
        with self._lock:
            self.defers[cause] += 1
        if self.on_defer is not None:
            try:
                self.on_defer(cause)
            except Exception:
                pass

    # -- read side ---------------------------------------------------------

    def frees_total(self) -> int:
        with self._lock:
            return sum(self.frees.values())

    def live_blocks(self) -> int:
        """Blocks currently alive per the ledger — must equal the
        pool's `in_use` whenever the ledger was attached from the
        pool's first alloc (the conservation check)."""
        with self._lock:
            return len(self._live)

    def snapshot(self) -> dict:
        """/debug/profile payload: cause totals, recent-window reuse /
        age quantiles, defers, and the conservation fields."""
        with self._lock:
            frees = dict(self.frees)
            reuse = list(self._reuse)
            ages = list(self._ages)
            out = {
                "admissions": self._tick,
                "births": self.births,
                "frees": frees,
                "frees_total": sum(frees.values()),
                "live_blocks": len(self._live),
                "defers": dict(self.defers),
                "spill": {
                    "spilled": self.spilled,
                    "demotions": self.spill_demotions,
                    "restores": self.spill_restores,
                    "drops": self.spill_drops,
                },
            }
        out["reuse_distance"] = {
            "count": len(reuse),
            "p50": sample_quantile(reuse, 0.50),
            "p95": sample_quantile(reuse, 0.95),
        }
        out["block_age"] = {
            "count": len(ages),
            "p50": sample_quantile(ages, 0.50),
            "p95": sample_quantile(ages, 0.95),
        }
        sp = out["spill"]
        # Device-block conservation (the original invariant) AND the
        # PR-19 content conservation: births − frees == live + spilled
        # once restores are netted out of births and spill demotions
        # out of the deaths (a demote keeps the content alive on the
        # host; a budget drop or failed restore kills it for real).
        content_alive = (
            (out["births"] - sp["restores"])
            - (out["frees_total"] - frees["spill"] + sp["drops"]))
        out["conserved"] = (out["births"] - out["frees_total"]
                            == out["live_blocks"]
                            and content_alive
                            == out["live_blocks"] + sp["spilled"]
                            and frees[UNATTRIBUTED] == 0)
        return out

    # -- chrome counter tracks --------------------------------------------

    def _emit_event(self) -> None:
        # caller holds the lock
        self._events.append({
            "name": "kv_evictions", "ph": "C",
            "ts": round(self._wall() * 1e6, 1), "pid": 1, "tid": 0,
            "args": {c: self.frees[c] for c in EVICTION_CAUSES},
        })

    def counter_events(self, *, prefix: str = "") -> list[dict]:
        """Chrome "C" events for `/debug/traces` (cumulative eviction
        counts per cause over time), names prefixed per model the same
        way as `PhaseProfiler.counter_events`."""
        with self._lock:
            evs = [dict(e) for e in self._events]
        if prefix:
            for e in evs:
                e["name"] = f"{prefix}.{e['name']}"
        return evs
