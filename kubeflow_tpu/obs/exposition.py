"""Strict Prometheus text-exposition parser + renderer.

Grown out of `ci/obs_check.py` (which still re-exports everything here
for its callers): once the fleet router started FEDERATING expositions
(`/fleet/metrics` merges every replica's `/metrics` into one document),
the parser stopped being a CI-only gate and became a runtime dependency
— so it lives in `obs/` where both the gate and the router can import
it without `ci/` leaking into the serving path.

The parser is intentionally pedantic where Prometheus' own parser is
forgiving: render bugs (a histogram that forgets `+Inf`, an unescaped
quote in a label) should fail loudly at the first parse, not corrupt
dashboards later. `render_families` is the exact inverse — its output
round-trips through `parse_exposition` unchanged, which is what makes
parse → merge → re-render federation safe to chain.
"""

from __future__ import annotations

import math

# -- strict exposition parser -------------------------------------------


class ExpositionError(ValueError):
    """A violation of the exposition contract (line number included)."""


def _unescape_label_value(raw: str, lineno: int) -> str:
    out = []
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "\\":
            if i + 1 >= len(raw):
                raise ExpositionError(
                    f"line {lineno}: dangling backslash in label value")
            nxt = raw[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:
                raise ExpositionError(
                    f"line {lineno}: bad escape \\{nxt} in label value")
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(body: str, lineno: int) -> dict[str, str]:
    """Parse the inside of `{...}` honoring escapes; quotes/commas
    inside label VALUES must not split pairs."""
    labels: dict[str, str] = {}
    i = 0
    n = len(body)
    while i < n:
        eq = body.find("=", i)
        if eq < 0:
            raise ExpositionError(f"line {lineno}: label without '='")
        name = body[i:eq].strip()
        if not name or not name.replace("_", "a").isalnum():
            raise ExpositionError(f"line {lineno}: bad label name {name!r}")
        if eq + 1 >= n or body[eq + 1] != '"':
            raise ExpositionError(
                f"line {lineno}: label value for {name} not quoted")
        j = eq + 2
        while j < n:
            if body[j] == "\\":
                j += 2
                continue
            if body[j] == '"':
                break
            j += 1
        if j >= n:
            raise ExpositionError(
                f"line {lineno}: unterminated label value for {name}")
        if name in labels:
            raise ExpositionError(f"line {lineno}: duplicate label {name}")
        labels[name] = _unescape_label_value(body[eq + 2:j], lineno)
        i = j + 1
        if i < n:
            if body[i] != ",":
                raise ExpositionError(
                    f"line {lineno}: expected ',' between labels, "
                    f"got {body[i]!r}")
            i += 1
    return labels


def _parse_value(raw: str, lineno: int) -> float:
    if raw in ("+Inf", "Inf"):
        return math.inf
    if raw == "-Inf":
        return -math.inf
    try:
        return float(raw)
    except ValueError:
        raise ExpositionError(
            f"line {lineno}: unparseable sample value {raw!r}") from None


_HISTOGRAM_SUFFIXES = ("_bucket", "_sum", "_count")


def parse_exposition(text: str) -> dict[str, dict]:
    """Parse + validate a Prometheus text exposition.

    Returns {family_name: {"type": str, "help": str, "samples":
    {(sample_name, ((label, value), ...)): float}}}. Raises
    ExpositionError on any contract violation.
    """
    families: dict[str, dict] = {}

    def family_of(sample_name: str, lineno: int) -> dict:
        if sample_name in families:
            return families[sample_name]
        for suffix in _HISTOGRAM_SUFFIXES:
            base = sample_name.removesuffix(suffix)
            if base != sample_name and base in families \
                    and families[base]["type"] == "histogram":
                return families[base]
        raise ExpositionError(
            f"line {lineno}: sample {sample_name!r} has no preceding "
            "# TYPE declaration")

    for lineno, line in enumerate(text.split("\n"), start=1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP "):].split(" ", 1)
            fam = families.setdefault(
                parts[0], {"type": None, "help": None, "samples": {}})
            fam["help"] = parts[1] if len(parts) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split(" ", 1)
            if len(parts) != 2 or parts[1] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise ExpositionError(f"line {lineno}: bad TYPE line")
            fam = families.setdefault(
                parts[0], {"type": None, "help": None, "samples": {}})
            if fam["type"] is not None:
                raise ExpositionError(
                    f"line {lineno}: duplicate TYPE for {parts[0]}")
            fam["type"] = parts[1]
            continue
        if line.startswith("#"):
            continue  # comment
        # sample line: name[{labels}] value
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                raise ExpositionError(f"line {lineno}: unbalanced braces")
            name = line[:brace]
            labels = _parse_labels(line[brace + 1:close], lineno)
            rest = line[close + 1:].strip()
        else:
            name, _, rest = line.partition(" ")
            labels = {}
            rest = rest.strip()
        if not name or not rest or " " in rest:
            raise ExpositionError(f"line {lineno}: malformed sample line")
        fam = family_of(name, lineno)
        if fam["type"] is None:
            raise ExpositionError(
                f"line {lineno}: sample {name!r} precedes its TYPE")
        key = (name, tuple(sorted(labels.items())))
        if key in fam["samples"]:
            raise ExpositionError(
                f"line {lineno}: duplicate series {name}{labels}")
        fam["samples"][key] = _parse_value(rest, lineno)

    for fname, fam in families.items():
        if fam["type"] is None:
            raise ExpositionError(f"family {fname}: HELP without TYPE")
        if fam["help"] is None:
            raise ExpositionError(f"family {fname}: TYPE without HELP")
        if not fam["samples"]:
            continue
        if fam["type"] == "counter":
            for (sname, labels), v in fam["samples"].items():
                if v < 0:
                    raise ExpositionError(
                        f"counter {sname}{dict(labels)} is negative ({v})")
        if fam["type"] == "histogram":
            _check_histogram(fname, fam)
    return families


def _check_histogram(fname: str, fam: dict) -> None:
    """Cumulative nondecreasing buckets, +Inf == _count, _sum present —
    per label-set (le excluded)."""
    by_labelset: dict[tuple, dict] = {}
    for (sname, labels), v in fam["samples"].items():
        ldict = dict(labels)
        le = ldict.pop("le", None)
        group = by_labelset.setdefault(
            tuple(sorted(ldict.items())),
            {"buckets": [], "sum": None, "count": None})
        if sname == fname + "_bucket":
            if le is None:
                raise ExpositionError(f"{sname}: bucket without le label")
            group["buckets"].append((_parse_value(le, 0), v))
        elif sname == fname + "_sum":
            group["sum"] = v
        elif sname == fname + "_count":
            group["count"] = v
        else:
            raise ExpositionError(
                f"{sname}: unexpected sample in histogram {fname}")
    for labelset, group in by_labelset.items():
        where = f"histogram {fname}{dict(labelset)}"
        if group["sum"] is None or group["count"] is None:
            raise ExpositionError(f"{where}: missing _sum or _count")
        if not group["buckets"]:
            raise ExpositionError(f"{where}: no buckets")
        les = [le for le, _ in group["buckets"]]
        if les != sorted(les):
            raise ExpositionError(f"{where}: buckets not in le order")
        if len(set(les)) != len(les):
            raise ExpositionError(f"{where}: duplicate le buckets")
        counts = [c for _, c in group["buckets"]]
        if any(b > a for b, a in zip(counts, counts[1:])):
            raise ExpositionError(f"{where}: bucket counts not cumulative")
        if les[-1] != math.inf:
            raise ExpositionError(f"{where}: last bucket is not +Inf")
        if counts[-1] != group["count"]:
            raise ExpositionError(
                f"{where}: +Inf bucket {counts[-1]} != _count "
                f"{group['count']}")


# -- renderer: the parser's inverse -------------------------------------


def _escape_label_value(v: str) -> str:
    # Exposition escapes (mirrors controlplane.metrics; duplicated so
    # obs never imports controlplane at module scope).
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_sample(name: str, labels: tuple, value: float) -> str:
    if labels:
        body = ",".join(
            f'{k}="{_escape_label_value(v)}"' for k, v in labels)
        return f"{name}{{{body}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


def render_families(families: dict[str, dict]) -> str:
    """Render a `parse_exposition`-shaped dict back to exposition text.

    The output re-parses to an equal dict: HELP/TYPE always emitted,
    histogram buckets grouped per label-set in ascending `le` order
    followed by `_sum`/`_count`, everything else sorted by (sample
    name, labels) for deterministic diffs.
    """
    lines: list[str] = []
    for fname in sorted(families):
        fam = families[fname]
        lines.append(f"# HELP {fname} {fam.get('help') or fname}")
        lines.append(f"# TYPE {fname} {fam['type']}")
        samples = fam["samples"]
        if fam["type"] != "histogram":
            for (sname, labels) in sorted(samples):
                lines.append(_fmt_sample(sname, labels,
                                         samples[(sname, labels)]))
            continue
        # Histogram: per label-set (le excluded) emit buckets ascending,
        # then _sum and _count — the order _check_histogram demands.
        groups: dict[tuple, dict] = {}
        for (sname, labels), v in samples.items():
            ldict = dict(labels)
            le = ldict.pop("le", None)
            g = groups.setdefault(tuple(sorted(ldict.items())),
                                  {"buckets": [], "sum": 0.0, "count": 0.0})
            if sname == fname + "_bucket":
                g["buckets"].append((_parse_value(le, 0), v))
            elif sname == fname + "_sum":
                g["sum"] = v
            elif sname == fname + "_count":
                g["count"] = v
        for labelset in sorted(groups):
            g = groups[labelset]
            for le, v in sorted(g["buckets"]):
                blabels = tuple(sorted(
                    dict(labelset, le="+Inf" if le == math.inf
                         else _fmt_value(le)).items()))
                lines.append(_fmt_sample(fname + "_bucket", blabels, v))
            lines.append(_fmt_sample(fname + "_sum", labelset, g["sum"]))
            lines.append(_fmt_sample(fname + "_count", labelset,
                                     g["count"]))
    return "\n".join(lines) + "\n"
