"""Histogram metric: Prometheus exposition-compatible latency buckets.

The control plane's `Registry` (controlplane/metrics.py) renders any
metric exposing `name`, `help`, `TYPE` and `expositions()`; Histogram
is deliberately standalone (no controlplane import) so the serving and
training layers can observe latencies without pulling the store in.

Exposition follows the text format exactly: per label set, cumulative
`_bucket{le="..."}` lines in ascending bucket order ending at
`le="+Inf"` (== `_count`), then `_sum` and `_count`. `observe` is a
single bisect + three additions under one lock — cheap enough for the
serving hot path.
"""

from __future__ import annotations

import bisect
import threading
from typing import Iterator

# Latency buckets (seconds): sub-ms workqueue pops through multi-second
# compiles. The classic prometheus default, extended one decade down.
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)
# Batch/queue-size buckets: powers of two up to the largest slot counts.
SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)
# Token-count buckets (prompt/prefill sizes): powers of two out to the
# longest context lengths served — used by the prefix-cache histogram
# (tokens computed vs reused per admission).
TOKEN_BUCKETS = (1.0, 4.0, 16.0, 64.0, 256.0, 1024.0, 4096.0, 16384.0,
                 65536.0)


def format_float(v: float) -> str:
    """Prometheus-style number formatting: integral floats render with
    one decimal place stripped to int-ish text (`1`, not `1.0`, for
    counts; bucket bounds keep their written form via repr)."""
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def sample_quantile(xs, q: float) -> float | None:
    """Linear-interpolated quantile over RAW samples — the exact-sample
    analog of `Histogram.quantile`'s within-bucket interpolation, and
    the one quantile definition every process-local summary in the repo
    uses (`StepTimer.summary`, `PhaseProfiler.snapshot`). A naive index
    pick (`xs[int(q * n)]`) disagrees with the histogram-side estimate
    by up to a full sample gap; this is the standard `q * (n - 1)`
    order-statistic interpolation instead."""
    if not xs:
        return None
    xs = sorted(xs)
    if len(xs) == 1:
        return float(xs[0])
    q = min(max(float(q), 0.0), 1.0)
    pos = q * (len(xs) - 1)
    lo = int(pos)
    frac = pos - lo
    if frac == 0.0 or lo + 1 >= len(xs):
        return float(xs[lo])
    return float(xs[lo] + (xs[lo + 1] - xs[lo]) * frac)


class Histogram:
    """Cumulative histogram with optional labels.

    `buckets` are upper bounds (exclusive of +Inf, which is implicit);
    they must be strictly increasing. Per label set the state is
    (per-bucket counts, sum, count) — cumulation happens at render so
    observe stays O(log buckets).
    """

    TYPE = "histogram"

    def __init__(self, name: str, help: str, registry=None,
                 *, buckets: tuple[float, ...] = LATENCY_BUCKETS):
        if not buckets:
            raise ValueError("histogram needs at least one bucket")
        bs = tuple(float(b) for b in buckets)
        if any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError(f"buckets must be strictly increasing: {bs}")
        self.name = name
        self.help = help
        self.buckets = bs
        self._lock = threading.Lock()
        # label key -> [counts per bucket (+Inf last), sum, count]
        self._data: dict[tuple[tuple[str, str], ...],
                         tuple[list[int], list[float]]] = {}
        if registry is not None:
            registry.register(self)

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        i = bisect.bisect_left(self.buckets, float(value))
        with self._lock:
            row = self._data.get(key)
            if row is None:
                row = ([0] * (len(self.buckets) + 1), [0.0, 0.0])
                self._data[key] = row
            row[0][i] += 1
            row[1][0] += float(value)
            row[1][1] += 1.0

    def seed(self, **labels: str) -> None:
        """Create the label set with ZERO observations. An all-zero row
        is a valid exposition (every bucket 0, `+Inf` == `_count` == 0,
        `_sum` 0), so seeded series appear on the first scrape — the
        histogram analog of `Counter.inc(0, **labels)` zero-seeding."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            if key not in self._data:
                self._data[key] = ([0] * (len(self.buckets) + 1),
                                   [0.0, 0.0])

    # -- read side ---------------------------------------------------------

    def quantile(self, q: float, **labels: str) -> float | None:
        """Estimate the q-quantile from bucket counts, prometheus
        `histogram_quantile` style: find the bucket where the cumulative
        count crosses `q * count`, then interpolate linearly inside it
        (a sample in the `+Inf` bucket clamps to the highest finite
        bound). None when the label set has no observations."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            row = self._data.get(key)
            if row is None or row[1][1] <= 0:
                return None
            counts = list(row[0])
            total = row[1][1]
        rank = min(max(float(q), 0.0), 1.0) * total
        acc = 0.0
        lo = 0.0
        for bound, c in zip(self.buckets, counts):
            if acc + c >= rank and c > 0:
                return lo + (bound - lo) * (rank - acc) / c
            acc += c
            lo = bound
        return self.buckets[-1]

    def count(self, **labels: str) -> int:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            row = self._data.get(key)
            return int(row[1][1]) if row else 0

    def sum(self, **labels: str) -> float:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            row = self._data.get(key)
            return row[1][0] if row else 0.0

    def samples(self):
        """(labels, count) pairs — the Counter-shaped view some generic
        consumers (collectors resetting gauges) expect."""
        with self._lock:
            return [(dict(k), row[1][1]) for k, row in self._data.items()]

    def expositions(self) -> Iterator[tuple[str, dict[str, str], float]]:
        """(sample_name, labels, value) triples in exposition order."""
        with self._lock:
            snap = [(dict(k), [list(row[0]), list(row[1])])
                    for k, row in sorted(self._data.items())]
        for labels, (counts, sum_count) in snap:
            acc = 0
            for b, c in zip(self.buckets, counts):
                acc += c
                yield (f"{self.name}_bucket",
                       {**labels, "le": format_float(b)}, float(acc))
            acc += counts[-1]
            yield (f"{self.name}_bucket", {**labels, "le": "+Inf"},
                   float(acc))
            yield f"{self.name}_sum", dict(labels), sum_count[0]
            yield f"{self.name}_count", dict(labels), sum_count[1]


def get_or_create_histogram(registry, name: str, help: str,
                            *, buckets: tuple[float, ...] = LATENCY_BUCKETS
                            ) -> Histogram:
    """Idempotent registration: several Trainer/app instances sharing a
    registry (the module default) must not register duplicate series."""
    existing = registry.get(name)
    if existing is not None:
        if not isinstance(existing, Histogram):
            raise ValueError(
                f"metric {name!r} already registered as {existing.TYPE}")
        return existing
    return Histogram(name, help, registry, buckets=buckets)
