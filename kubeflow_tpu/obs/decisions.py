"""Decision ledger: conservation-checked accounting for the control loop.

The fleet controller (`fleet/control.py`) is only trustworthy if every
decision it takes — and every decision it *declines* to take — is a
first-class observable. This module is the book: a pure-python sidecar
(metric-free, importable anywhere) where every policy evaluation is
booked into exactly ONE outcome from a closed set, and every fired
action carries its evidence snapshot in and its post-window verdict
out.

Conservation invariant (asserted by tests and `ci/obs_check control`):

    evaluations == sum(outcomes over all causes)

i.e. no evaluation vanishes un-booked and none is double-counted — the
same structural discipline as `CacheLedger` (births - frees == in_use)
and the goodput ledger (phase sums == wall). An actuator that throws is
booked `actuator_failed`, never `fired`, so the fired count is a count
of actions that actually went out.

The ledger is metric-free; the router binds `on_decision`/`on_action`
to real counters (`fleet_control_decisions_total{policy,outcome}`,
`fleet_control_actions_total{policy,action}`), the same wiring idiom
as `PhaseProfiler.on_phase` and `CacheLedger.on_free`.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

# Closed set of evaluation outcomes. These become the `outcome` label on
# `fleet_control_decisions_total`, so the set is CLOSED by design:
#   fired                 — breach confirmed, actuator ran successfully
#   suppressed_hysteresis — signal breached (or is still above the clear
#                           level) but the policy is latched from a prior
#                           fire; re-firing waits for the signal to drop
#                           below the clear band
#   suppressed_cooldown   — breach confirmed but the policy fired too
#                           recently; cooling down
#   below_threshold       — nothing to do: signal is healthy
#   actuator_failed       — breach confirmed, fire attempted, actuator
#                           raised; booked here so `fired` only ever
#                           counts actions that actually went out
OUTCOMES = ("fired", "suppressed_hysteresis", "suppressed_cooldown",
            "below_threshold", "actuator_failed")

# Post-window verdict on a fired action: did the signal that justified
# the fire actually recover inside the policy's verify window?
VERDICTS = ("pending", "recovered", "not_recovered")

# Audit records kept for `GET /fleet/decisions`. Bounds memory; the
# counters underneath are cumulative and never truncate.
_MAX_RECORDS = 256


class DecisionLedger:
    """Accounting for one controller's policy evaluations.

    The controller calls `note(policy, outcome, ...)` exactly once per
    evaluation; for `fired`/`actuator_failed` outcomes it passes the
    evidence snapshot (signal value, threshold, replica counts — the
    facts the decision was made on) and, when fired, the action name.
    Later it calls `resolve(decision_id, verdict, ...)` once the verify
    window has elapsed and the signal has been re-read.

    Hook exceptions are swallowed: the ledger must never crash the
    control loop it is auditing.
    """

    def __init__(self, *, max_records: int = _MAX_RECORDS,
                 wall: Callable[[], float] = time.time):
        self._lock = threading.Lock()
        self._wall = wall
        self.evaluations = 0
        self.outcomes = {o: 0 for o in OUTCOMES}
        # per policy: {outcome: count}; grown on first sight so the
        # snapshot shows exactly the policies that were evaluated.
        self._by_policy: dict[str, dict[str, int]] = {}
        self.verdicts = {v: 0 for v in VERDICTS}
        self._records: deque = deque(maxlen=max_records)
        self._by_id: dict[int, dict] = {}
        self._next_id = 0
        # Bound by the consuming layer to real counters.
        self.on_decision: Callable[[str, str], None] | None = None
        self.on_action: Callable[[str, str], None] | None = None

    # -- write side --------------------------------------------------------

    def note(self, policy: str, outcome: str, *,
             action: str | None = None,
             evidence: dict | None = None) -> dict:
        """Book one evaluation into exactly one outcome. Returns the
        audit record; for fired outcomes the caller keeps its `id` to
        `resolve()` the verdict after the verify window."""
        if outcome not in OUTCOMES:
            raise ValueError(f"unknown outcome {outcome!r}")
        if outcome == "fired" and not action:
            raise ValueError("fired decisions must name their action")
        rec = {
            "id": None,
            "wall": self._wall(),
            "policy": policy,
            "outcome": outcome,
            "action": action,
            "evidence": dict(evidence or {}),
            "verdict": "pending" if outcome == "fired" else None,
            "verdict_evidence": None,
        }
        with self._lock:
            self.evaluations += 1
            self.outcomes[outcome] += 1
            per = self._by_policy.setdefault(
                policy, {o: 0 for o in OUTCOMES})
            per[outcome] += 1
            if outcome == "fired":
                rec["id"] = self._next_id
                self._next_id += 1
                self.verdicts["pending"] += 1
                self._by_id[rec["id"]] = rec
                # evict the oldest pending index entry once the deque
                # rolls it out, so _by_id stays bounded too
                if (len(self._records) == self._records.maxlen
                        and self._records[0].get("id") is not None):
                    self._by_id.pop(self._records[0]["id"], None)
            self._records.append(rec)
        self._hook(self.on_decision, policy, outcome)
        if outcome == "fired":
            self._hook(self.on_action, policy, action)
        return rec

    def resolve(self, decision_id: int, verdict: str, *,
                evidence: dict | None = None) -> bool:
        """Book the post-window verdict on a fired decision. Returns
        False when the id is unknown or already resolved."""
        if verdict not in VERDICTS or verdict == "pending":
            raise ValueError(f"unknown verdict {verdict!r}")
        with self._lock:
            rec = self._by_id.get(decision_id)
            if rec is None or rec["verdict"] != "pending":
                return False
            rec["verdict"] = verdict
            rec["verdict_evidence"] = dict(evidence or {})
            self.verdicts["pending"] -= 1
            self.verdicts[verdict] += 1
        return True

    # -- read side ---------------------------------------------------------

    @property
    def conserved(self) -> bool:
        with self._lock:
            return self.evaluations == sum(self.outcomes.values())

    def records(self, limit: int | None = None) -> list[dict]:
        """Audit trail, oldest first (evidence dicts are shallow-copied
        so callers can jsonify without racing the controller)."""
        with self._lock:
            recs = [dict(r) for r in self._records]
        return recs[-limit:] if limit else recs

    def pending(self) -> list[dict]:
        """Fired decisions still awaiting their verdict."""
        with self._lock:
            return [dict(r) for r in self._by_id.values()
                    if r["verdict"] == "pending"]

    def snapshot(self) -> dict:
        """Jsonable summary for `GET /fleet/decisions`."""
        with self._lock:
            return {
                "evaluations": self.evaluations,
                "outcomes": dict(self.outcomes),
                "by_policy": {p: dict(c)
                              for p, c in sorted(self._by_policy.items())},
                "verdicts": dict(self.verdicts),
                "conserved": (self.evaluations
                              == sum(self.outcomes.values())),
            }

    @staticmethod
    def _hook(fn, *args) -> None:
        if fn is None:
            return
        try:
            fn(*args)
        except Exception:
            pass
