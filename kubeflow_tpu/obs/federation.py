"""Fleet metrics federation: merge N replica expositions into one.

The router scrapes each ready replica's `/metrics` and serves the
merged document at `/fleet/metrics`, so one scrape answers for the
whole fleet. This module is the sans-io math: it takes already-fetched
exposition TEXTS keyed by replica id and returns one merged exposition
that round-trips through the strict parser (`obs.exposition`).

Merge rules, per family across replicas:

- **counters** are summed per (sample name, labels) — fleet totals.
- **gauges** are summed too: every fleet gauge we export is an amount
  (replicas per state, KV blocks in use, queue depth), where the fleet
  value IS the sum. Info-style gauges (`serving_attention_impl`) sum
  into a replica count per impl, which reads correctly as "N replicas
  run this impl".
- **histograms** are merged on the UNION of bucket boundaries. A
  replica that lacks a boundary `u` contributes its cumulative count at
  its largest own `le <= u` (cumulative counts are nondecreasing step
  functions, so this floor interpolation is exact when grids match and
  conservative when they do not). `_sum`/`_count` add. The result
  preserves every histogram invariant the parser checks.
- a family TYPE disagreement across replicas is an `ExpositionError` —
  a fleet where two replicas disagree about what a name means is a
  deploy bug worth failing the scrape over.

A `fleet_federation_up{replica=...}` gauge (1 scraped, 0 unreachable or
unparseable) is appended so the merged document itself says which
replicas it covers; the `replica` values pass through a
`cardinality.LabelGuard` so a churning fleet cannot grow the label set
without bound.
"""

from __future__ import annotations

import math

from .cardinality import LabelGuard
from .exposition import (ExpositionError, _fmt_value, parse_exposition,
                         render_families)

__all__ = ["ExpositionError", "federate", "merge_families"]


def _merge_histogram(fname: str, variants: list[dict]) -> dict:
    """Merge histogram families on the union of bucket grids."""
    # per label-set (le excluded): list of (le->cum dict, sum, count)
    groups: dict[tuple, list[dict]] = {}
    for fam in variants:
        per_ls: dict[tuple, dict] = {}
        for (sname, labels), v in fam["samples"].items():
            ldict = dict(labels)
            le = ldict.pop("le", None)
            g = per_ls.setdefault(
                tuple(sorted(ldict.items())),
                {"cum": {}, "sum": 0.0, "count": 0.0})
            if sname == fname + "_bucket":
                g["cum"][float(le) if le not in ("+Inf", "Inf")
                         else math.inf] = v
            elif sname == fname + "_sum":
                g["sum"] = v
            elif sname == fname + "_count":
                g["count"] = v
        for ls, g in per_ls.items():
            groups.setdefault(ls, []).append(g)

    samples: dict[tuple, float] = {}
    for ls, parts in groups.items():
        grid = sorted({le for g in parts for le in g["cum"]})
        for u in grid:
            total = 0.0
            for g in parts:
                # floor interpolation: cumulative count at the largest
                # own boundary <= u (0 below the first boundary)
                own = [le for le in g["cum"] if le <= u]
                if own:
                    total += g["cum"][max(own)]
            blabels = tuple(sorted(
                dict(ls, le=_fmt_value(u)).items()))
            samples[(fname + "_bucket", blabels)] = total
        samples[(fname + "_sum", ls)] = sum(g["sum"] for g in parts)
        samples[(fname + "_count", ls)] = sum(g["count"] for g in parts)
    return samples


def merge_families(expositions: list[dict[str, dict]]) -> dict[str, dict]:
    """Merge parsed expositions (see `parse_exposition`) into one dict
    of the same shape. Raises ExpositionError on TYPE conflicts."""
    merged: dict[str, dict] = {}
    variants: dict[str, list[dict]] = {}
    for families in expositions:
        for fname, fam in families.items():
            if fname in merged:
                if merged[fname]["type"] != fam["type"]:
                    raise ExpositionError(
                        f"family {fname}: TYPE conflict across replicas "
                        f"({merged[fname]['type']} vs {fam['type']})")
            else:
                merged[fname] = {"type": fam["type"],
                                 "help": fam["help"], "samples": {}}
            variants.setdefault(fname, []).append(fam)
    for fname, fams in variants.items():
        if merged[fname]["type"] == "histogram":
            merged[fname]["samples"] = _merge_histogram(fname, fams)
            continue
        out = merged[fname]["samples"]
        for fam in fams:
            for key, v in fam["samples"].items():
                out[key] = out.get(key, 0.0) + v
    return merged


def federate(scrapes: dict[str, str | None],
             guard: LabelGuard | None = None,
             versions: dict[str, str] | None = None,
             version_guard: LabelGuard | None = None) -> str:
    """Scrape texts keyed by replica id (None = unreachable) -> one
    merged exposition text. Replicas whose text fails the strict parse
    are treated as down rather than poisoning the merge. `versions`
    (replica id -> model-version label, ISSUE 18) adds PARALLEL
    `fleet_federation_up{replica,version}` series beside the plain
    `{replica}` ones — same family, unlabeled-by-version totals
    untouched (the PR 13 pattern) — so one federated scrape says which
    weights each covered replica was serving; values pass
    `version_guard` (capped) before becoming labels."""
    guard = guard or LabelGuard()
    versions = versions or {}
    version_guard = version_guard or LabelGuard()
    parsed: list[dict[str, dict]] = []
    up: dict[str, float] = {}
    for rid, text in scrapes.items():
        label = guard.admit(rid)
        if text is None:
            up[label] = min(up.get(label, 0.0), 0.0)
            continue
        try:
            parsed.append(parse_exposition(text))
        except ExpositionError:
            up[label] = min(up.get(label, 0.0), 0.0)
            continue
        up[label] = max(up.get(label, 1.0), 1.0)
    merged = merge_families(parsed)
    samples = {
        ("fleet_federation_up", (("replica", label),)): v
        for label, v in up.items()
    }
    # version-labelled parallel series (never replaces the plain ones)
    ver_by_label = {guard.admit(rid): v
                    for rid, v in versions.items() if v}
    for label, v in up.items():
        ver = ver_by_label.get(label)
        if ver:
            key = ("fleet_federation_up",
                   tuple(sorted((("replica", label),
                                 ("version",
                                  version_guard.admit(ver))))))
            samples[key] = max(samples.get(key, 0.0), v)
    merged["fleet_federation_up"] = {
        "type": "gauge",
        "help": "1 if the replica's /metrics was scraped and strictly "
                "parsed into this federation, 0 otherwise; "
                "version-labelled series say which model version the "
                "covered replica was serving",
        "samples": samples,
    }
    return render_families(merged)
