"""Shared aiohttp observability endpoints.

Every HTTP-speaking process in the platform (dashboard, serving
replica, fleet router) exposes the same two doors — `/metrics` and
`/debug/traces` — and until ISSUE 6 each app re-implemented them as
inline closures. These factories are that closure, once: hand them a
registry/tracer and mount the returned handler.

Kept in its own module (not `obs/__init__`) so importing `obs` never
pulls aiohttp into processes that don't serve HTTP (the Trainer).
"""

from __future__ import annotations

from aiohttp import web

from .tracing import Tracer, traces_response_payload


def metrics_handler(registry):
    """GET /metrics handler over a `controlplane.metrics.Registry`."""

    async def render_metrics(_request: web.Request) -> web.Response:
        return web.Response(text=registry.render(),
                            content_type="text/plain")

    return render_metrics


def traces_handler(tracer: Tracer):
    """GET /debug/traces handler over a Tracer. Query contract lives in
    `traces_response_payload`; a bad `?limit=` is the caller's fault
    (400), not a crash."""

    async def debug_traces(request: web.Request) -> web.Response:
        try:
            payload = traces_response_payload(tracer,
                                              request.rel_url.query)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e)) from None
        return web.json_response(payload)

    return debug_traces


def mount_observability(app: web.Application, *, registry,
                        tracer: Tracer) -> None:
    """Mount GET /metrics and GET /debug/traces on `app`."""
    app.router.add_get("/metrics", metrics_handler(registry))
    app.router.add_get("/debug/traces", traces_handler(tracer))
