"""Per-request token timelines: where did this request's time go?

Histograms answer "how is the fleet doing"; a timeline answers the
next question an operator asks — "what happened to THIS request". The
`ContinuousBatcher` stamps one `RequestTimeline` per request with its
structural events (enqueue, admit with prefill split, preempt/resume,
finish) plus the timestamp of EVERY emitted token, and the serving app
exposes the result at `/v1/requests/{id}/timeline`.

Token timestamps are kept as a flat float list, not event dicts: a
4k-token generation costs one list of floats, and inter-token latency
(ITL) falls out as consecutive differences. Derived numbers:

- `queue_wait_s` — enqueue -> admit (the scheduling delay),
- `ttft_s`      — enqueue -> first token,
- ITL stats     — gaps between consecutive tokens, EXCLUDING gaps that
  span a preempt/resume hole (those measure scheduling, not decode;
  they are visible as events instead).

Everything takes an injectable clock so tests can assert exact math.
`TimelineStore` is the bounded keep — finished or not, oldest request
evicted first — that the debug endpoint reads from.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable

# Structural events per timeline are bounded: a pathological
# preempt/resume flap must not grow one request without limit.
MAX_EVENTS = 256
# Token timestamps are bounded by max_new upstream, but cap anyway.
MAX_TOKENS = 65536


class RequestTimeline:
    """Event + token-timestamp record for one request."""

    __slots__ = ("request_id", "model", "tenant", "prompt_tokens",
                 "max_new", "events", "tokens", "_clock", "_itl_break",
                 "done")

    def __init__(self, request_id: str, *, model: str = "",
                 tenant: str = "", prompt_tokens: int = 0,
                 max_new: int = 0,
                 clock: Callable[[], float] | None = None):
        self.request_id = request_id
        self.model = model
        self.tenant = tenant
        # workload shape, stamped by the batcher at enqueue; together
        # with the enqueue instant this makes any stored timeline
        # replayable (the scenario recorder reads exactly these)
        self.prompt_tokens = prompt_tokens
        self.max_new = max_new
        self._clock = clock or time.monotonic
        self.events: list[tuple[float, str, dict]] = []
        self.tokens: list[float] = []
        # next token gap spans a preempt/resume hole -> not an ITL
        self._itl_break = True  # first token has no predecessor
        self.done = False

    def event(self, kind: str, **detail: Any) -> None:
        if len(self.events) < MAX_EVENTS:
            self.events.append((self._clock(), kind, detail))
        if kind in ("preempt", "resume"):
            self._itl_break = True
        if kind == "finish":
            self.done = True

    def token(self) -> float | None:
        """Record one emitted token. Returns the inter-token gap in
        seconds, or None when the gap is not an ITL (first token, or
        first token after a preempt/resume hole)."""
        t = self._clock()
        gap = None
        if self.tokens and not self._itl_break:
            gap = t - self.tokens[-1]
        self._itl_break = False
        if len(self.tokens) < MAX_TOKENS:
            self.tokens.append(t)
        return gap

    # -- derived -----------------------------------------------------------

    def _first(self, kind: str) -> float | None:
        for t, k, _ in self.events:
            if k == kind:
                return t
        return None

    @property
    def queue_wait_s(self) -> float | None:
        t0, t1 = self._first("enqueue"), self._first("admit")
        return (t1 - t0) if t0 is not None and t1 is not None else None

    @property
    def ttft_s(self) -> float | None:
        t0 = self._first("enqueue")
        return (self.tokens[0] - t0) \
            if t0 is not None and self.tokens else None

    def itls(self) -> list[float]:
        """Inter-token gaps, excluding gaps across preempt/resume
        holes (recomputed from events, so it works on stored
        timelines too)."""
        holes = sorted(t for t, k, _ in self.events
                       if k in ("preempt", "resume"))
        out = []
        for a, b in zip(self.tokens, self.tokens[1:]):
            if any(a <= h <= b for h in holes):
                continue
            out.append(b - a)
        return out

    def to_dict(self) -> dict[str, Any]:
        """JSON shape for `/v1/requests/{id}/timeline`. Times are
        seconds RELATIVE to enqueue (monotonic clock — absolute values
        mean nothing to a client)."""
        t0 = self._first("enqueue")
        if t0 is None:
            t0 = self.events[0][0] if self.events else 0.0
        itls = self.itls()
        itls_sorted = sorted(itls)

        def pct(p: float) -> float | None:
            if not itls_sorted:
                return None
            return itls_sorted[min(len(itls_sorted) - 1,
                                   int(p * len(itls_sorted)))]

        return {
            "request_id": self.request_id,
            "model": self.model,
            "tenant": self.tenant,
            "prompt_tokens": self.prompt_tokens,
            "max_new": self.max_new,
            "output_tokens": len(self.tokens),
            # absolute arrival on the timeline's own clock: relative
            # times suffice for debugging ONE request, but recording a
            # replayable trace needs cross-request ordering
            "enqueue_monotonic_s": round(t0, 6),
            "done": self.done,
            "events": [
                {"t": round(t - t0, 6), "kind": k, **detail}
                for t, k, detail in self.events
            ],
            "tokens": len(self.tokens),
            "token_times": [round(t - t0, 6) for t in self.tokens],
            "queue_wait_s": self.queue_wait_s,
            "ttft_s": self.ttft_s,
            "itl": {
                "count": len(itls),
                "mean_s": (sum(itls) / len(itls)) if itls else None,
                "p50_s": pct(0.50),
                "p95_s": pct(0.95),
                "max_s": max(itls) if itls else None,
            },
        }


class TimelineStore:
    """Bounded, thread-safe keep of recent timelines by request id.

    Both live and finished requests stay queryable; the oldest entry
    is evicted first. Duplicate ids (client-chosen) overwrite — last
    writer wins, matching what an operator would want to inspect."""

    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._items: "collections.OrderedDict[str, RequestTimeline]" = \
            collections.OrderedDict()

    def add(self, tl: RequestTimeline) -> None:
        with self._lock:
            self._items.pop(tl.request_id, None)
            self._items[tl.request_id] = tl
            while len(self._items) > self.capacity:
                self._items.popitem(last=False)

    def get(self, request_id: str) -> RequestTimeline | None:
        with self._lock:
            return self._items.get(request_id)

    def ids(self) -> list[str]:
        """Request ids currently stored, oldest first."""
        with self._lock:
            return list(self._items)

    def snapshot(self) -> list[RequestTimeline]:
        """Stored timelines, oldest first (the scenario recorder's
        enumeration surface)."""
        with self._lock:
            return list(self._items.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)
