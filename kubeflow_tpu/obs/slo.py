"""Declarative SLOs evaluated into multi-window burn-rate gauges.

An `Slo` names an objective ("99% of interactive requests see TTFT
under 500 ms"); the `SloEngine` turns a stream of good/bad events into
**burn rates** over a short and a long window:

    burn = bad_fraction_in_window / error_budget,
    error_budget = 1 - objective

Burn 1.0 means the service is spending its error budget exactly as
fast as the objective allows; the classic multi-window alert fires
when BOTH windows burn hot (short window = it is happening now, long
window = it is not just a blip). We expose the raw rates and leave the
AND to the alerting layer.

The engine IS a registry metric (duck-typed like `obs.Histogram`:
`name`/`help`/`TYPE`/`expositions()`), so wiring is one
`registry.register(engine)` and the gauge is computed live at scrape
time. Every `slo x window` pair is always emitted — zero-seeded — so
rates are well-defined from the first scrape even before traffic.

Feeders run on the serving hot path and the batcher worker thread, so
`observe`/`record` are a deque append under one lock; windows are
pruned lazily. The clock is injectable for tests.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Iterator

# Events kept per SLO: bounds memory if a window is set absurdly long
# or traffic is extreme; at the default 600 s long window this is only
# reached past ~27 events/s, where subsampling barely moves a fraction.
MAX_EVENTS_PER_SLO = 16384

WINDOWS = ("short", "long")


class Slo:
    """One objective. `objective` is the good-fraction target (0,1);
    `threshold_s` lets latency feeders call `observe(name, seconds)`
    instead of pre-classifying good/bad themselves."""

    __slots__ = ("name", "objective", "threshold_s", "description")

    def __init__(self, name: str, objective: float,
                 threshold_s: float | None = None, description: str = ""):
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"slo {name!r}: objective must be in (0, 1), "
                f"got {objective}")
        if threshold_s is not None and threshold_s <= 0:
            raise ValueError(
                f"slo {name!r}: threshold_s must be positive")
        self.name = name
        self.objective = objective
        self.threshold_s = threshold_s
        self.description = description

    @property
    def error_budget(self) -> float:
        return 1.0 - self.objective


class SloEngine:
    """Burn-rate evaluator over a set of Slos; also the
    `slo_burn_rate{slo,window}` gauge metric."""

    name = "slo_burn_rate"
    help = ("error-budget burn rate per SLO and window (1.0 = spending "
            "budget exactly at the objective's rate; >1 = burning hot)")
    TYPE = "gauge"

    def __init__(self, slos: Iterator[Slo] | list[Slo], *,
                 short_window_s: float = 60.0,
                 long_window_s: float = 600.0,
                 clock: Callable[[], float] | None = None):
        slos = list(slos)
        if len({s.name for s in slos}) != len(slos):
            raise ValueError("duplicate SLO names")
        if not short_window_s < long_window_s:
            raise ValueError("short window must be shorter than long")
        self.slos: dict[str, Slo] = {s.name: s for s in slos}
        self.windows = {"short": float(short_window_s),
                        "long": float(long_window_s)}
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._budget: SloBudgetGauge | None = None
        # per slo: deque of (t, bad) — bad is 0/1
        self._events: dict[str, collections.deque] = {
            s.name: collections.deque(maxlen=MAX_EVENTS_PER_SLO)
            for s in slos}

    def add(self, slo: Slo) -> None:
        """Add an objective to a live engine. A no-op when the name
        already exists (first definition wins — two owners sharing one
        registry must agree on the objective, and the shared engine is
        the one place they meet)."""
        with self._lock:
            if slo.name in self.slos:
                return
            self.slos[slo.name] = slo
            self._events[slo.name] = collections.deque(
                maxlen=MAX_EVENTS_PER_SLO)

    # -- feed side ---------------------------------------------------------

    def record(self, name: str, good: bool) -> None:
        """One pre-classified event against SLO `name`. Unknown names
        are dropped silently: feeders must never crash the fed path."""
        dq = self._events.get(name)
        if dq is None:
            return
        with self._lock:
            dq.append((self._clock(), 0 if good else 1))

    def observe(self, name: str, seconds: float) -> None:
        """One latency sample against a threshold SLO."""
        slo = self.slos.get(name)
        if slo is None or slo.threshold_s is None:
            return
        self.record(name, seconds <= slo.threshold_s)

    # -- read side ---------------------------------------------------------

    def burn_rates(self) -> dict[tuple[str, str], float]:
        """{(slo, window): burn}. Windows with no events burn 0.0."""
        now = self._clock()
        horizon = now - self.windows["long"]
        out: dict[tuple[str, str], float] = {}
        with self._lock:
            for name, dq in self._events.items():
                while dq and dq[0][0] < horizon:
                    dq.popleft()
                snap = list(dq)
                slo = self.slos[name]
                for wname in WINDOWS:
                    cutoff = now - self.windows[wname]
                    total = bad = 0
                    for t, b in reversed(snap):
                        if t < cutoff:
                            break
                        total += 1
                        bad += b
                    frac = (bad / total) if total else 0.0
                    out[(name, wname)] = frac / slo.error_budget
        return out

    def expositions(self) -> Iterator[tuple[str, dict[str, str], float]]:
        rates = self.burn_rates()
        for name in sorted(self.slos):
            for wname in WINDOWS:
                yield (self.name, {"slo": name, "window": wname},
                       rates[(name, wname)])

    def budget_gauge(self) -> "SloBudgetGauge":
        """The engine's companion `slo_error_budget_remaining` metric
        (one instance per engine — the Registry dedupes by name, so a
        second family cannot come from the engine object itself)."""
        if self._budget is None:
            self._budget = SloBudgetGauge(self)
        return self._budget


class SloBudgetGauge:
    """Remaining error budget per SLO, as a fraction of the long
    window's budget: 1 - long-window burn. 1.0 = untouched, 0.0 =
    spending exactly at the objective's rate, negative = overspent.
    Operators and the fleet controller both want "how much runway is
    left", not just "how fast is it burning" — this is that number,
    computed live at scrape time from the same event windows as
    `slo_burn_rate`. Every SLO is always emitted (zero-seeded: an
    event-free window burns 0, so the budget reads a full 1.0)."""

    name = "slo_error_budget_remaining"
    help = ("fraction of the error budget left in the long burn "
            "window (1 - long-window burn rate; 1 = untouched, "
            "0 = spending at the objective's rate, negative = "
            "overspent)")
    TYPE = "gauge"

    def __init__(self, engine: SloEngine):
        self._engine = engine

    def expositions(self) -> Iterator[tuple[str, dict[str, str], float]]:
        rates = self._engine.burn_rates()
        for name in sorted(self._engine.slos):
            yield (self.name, {"slo": name},
                   1.0 - rates[(name, "long")])


def register_budget_gauge(registry, engine: SloEngine) -> None:
    """Idempotently register `engine`'s budget gauge on `registry`.
    Callers that register an engine directly (rather than through
    `get_or_create_slo_engine`) use this to get the companion family."""
    if registry.get(SloBudgetGauge.name) is None:
        try:
            registry.register(engine.budget_gauge())
        except ValueError:
            pass  # raced: the registry already carries one


def get_or_create_slo_engine(registry, slos, *,
                             short_window_s: float = 60.0,
                             long_window_s: float = 600.0,
                             clock: Callable[[], float] | None = None):
    """One burn-rate engine per registry.

    The engine IS the `slo_burn_rate` metric, so a registry can hold
    exactly one; every component that wants objectives on a shared
    registry (a serving app and a coordinator in one test process, or
    several apps behind one /metrics) must feed the same instance.
    Registers a fresh engine when the registry has none, otherwise
    merges the requested `slos` into the existing engine (first
    definition of a name wins) and returns it.
    """
    engine = registry.get("slo_burn_rate")
    if engine is None:
        engine = SloEngine(slos, short_window_s=short_window_s,
                           long_window_s=long_window_s, clock=clock)
        try:
            registry.register(engine)
        except ValueError:
            engine = registry.get("slo_burn_rate") or engine
        else:
            register_budget_gauge(registry, engine)
            return engine
    for slo in slos:
        engine.add(slo)
    register_budget_gauge(registry, engine)
    return engine
