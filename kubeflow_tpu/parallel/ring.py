"""Sequence/context parallelism: ring attention + Ulysses head-scatter.

Long-context attention over a sequence-sharded batch, the two TPU-idiomatic
layouts (SURVEY.md §5 "long-context"):

- **Ring attention** (`ring_attention`, `ring_attention_sharded`): each
  device keeps its Q shard resident and streams K/V shards around the ICI
  ring with `jax.lax.ppermute`, accumulating blockwise online-softmax
  partial results. O(s/N) activation memory per device, neighbor-only
  collectives (rides ICI links, never DCN). Explicit collectives via
  `shard_map` — this is deliberately NOT left to XLA: GSPMD would
  all-gather the full K/V.

- **Ulysses** (`ulysses_attention`): all-to-all swaps the sequence shard
  for a head shard, runs *full* local attention per head group, and swaps
  back. Cheaper when heads >= ring size and sequence fits after the swap;
  two all-to-alls instead of N-1 permutes.

Reference parity: the reference has no attention code of any kind
(SURVEY.md §2b row "SP/CP, ring attention"); this subsystem is green-field
TPU design.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.ops.attention import NEG_INF
from kubeflow_tpu.parallel import mesh as mesh_lib


def _block_attend(q, k, v, mask):
    """One blockwise-attention accumulation step (grouped-query, fp32).

    q: [b, sq, n_kv, g, hd]   (queries pre-grouped per kv head)
    k, v: [b, sk, n_kv, hd]
    mask: [b, sq, sk] bool (True = attend)
    Returns unnormalized (o, m, l) for online-softmax merging:
      o: [b, sq, n_kv, g, hd], m/l: [b, sq, n_kv, g]
    """
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bsngh,btnh->bngst", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                       # [b, n_kv, g, sq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bngst,btnh->bngsh", p, v.astype(jnp.float32))
    # rearrange to [b, sq, n_kv, g, ...] so seq leads like q/k/v
    perm = (0, 3, 1, 2)
    return (
        jnp.transpose(o, (0, 3, 1, 2, 4)),
        jnp.transpose(m, perm),
        jnp.transpose(l, perm),
    )


def ring_attention(
    q: jnp.ndarray,  # [b, s_local, n_q, hd]
    k: jnp.ndarray,  # [b, s_local, n_kv, hd]
    v: jnp.ndarray,  # [b, s_local, n_kv, hd]
    *,
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    """Ring attention over sequence shards. Call inside `shard_map`.

    The global sequence is the concatenation of per-device shards in
    axis-index order. K/V rotate one hop per step (N-1 ppermutes for an
    N-device ring) while each block's contribution merges into an
    online-softmax accumulator — numerically identical to full softmax
    attention over the gathered sequence.

    Causal masking is by *global* position, derived from the axis index of
    the device each K/V block originated on; fully-future blocks still
    execute (static schedule — no data-dependent control flow under jit)
    but contribute zero weight.
    """
    size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s, n_q, hd = q.shape
    n_kv = k.shape[2]
    assert n_q % n_kv == 0, (n_q, n_kv)
    g = n_q // n_kv
    qg = q.reshape(b, s, n_kv, g, hd)

    local_pos = jnp.arange(s, dtype=jnp.int32)
    q_pos = my_idx * s + local_pos                      # [s] global positions

    perm = [(i, (i + 1) % size) for i in range(size)]   # rotate k/v upward

    # Static unrolled ring (size is a compile-time constant under shard_map):
    # exactly size-1 ppermute hops — the last block needs no onward rotation.
    o = jnp.zeros((b, s, n_kv, g, hd), jnp.float32)
    m = jnp.full((b, s, n_kv, g), NEG_INF, jnp.float32)
    l = jnp.zeros((b, s, n_kv, g), jnp.float32)
    k_blk, v_blk = k, v
    for i in range(size):
        # Block i arrived after i hops: it originated on device my_idx - i.
        src = (my_idx - i) % size
        kv_pos = src * s + local_pos
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
        else:
            mask = jnp.ones((s, s), dtype=bool)
        mask = jnp.broadcast_to(mask, (b, s, s))
        o_i, m_i, l_i = _block_attend(qg, k_blk, v_blk, mask)
        m_new = jnp.maximum(m, m_i)
        a = jnp.exp(m - m_new)
        a_i = jnp.exp(m_i - m_new)
        o = o * a[..., None] + o_i * a_i[..., None]
        l = l * a + l_i * a_i
        m = m_new
        if i + 1 < size:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    # Causal guarantees every row attends at least to itself, so l > 0.
    out = o / l[..., None]
    return out.reshape(b, s, n_q, hd).astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,  # [b, s_global, n_q, hd]
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    seq_axis: str = mesh_lib.FSDP_AXIS,
    causal: bool = True,
) -> jnp.ndarray:
    """shard_map wrapper: sequence dim sharded over `seq_axis`, the rest
    replicated across it. Context parallelism conventionally reuses the
    fsdp device axis as the sequence axis (mesh.py axis convention)."""
    n = mesh.shape[seq_axis]
    if q.shape[1] % n != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by "
            f"{seq_axis}={n}"
        )
    spec = P(None, seq_axis, None, None)
    fn = mesh_lib.shard_map(
        functools.partial(ring_attention, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# ------------------------------------------------- ring x flash kernel
#
# The XLA ring above materializes per-block [s_local, s_local] fp32
# logits; this variant runs each block through the Pallas flash kernel
# (ops.pallas.flash_attention) instead — fused online softmax in VMEM,
# MXU fp32 accumulation — and adds a real skip: fully-future blocks
# execute a zero-cost lax.cond branch rather than computing logits and
# masking them to -inf.
#
# Backward is the ring-flash decomposition: flash's bwd formula with the
# GLOBAL row lse and delta = rowsum(do * o_final) splits cleanly along
# KV blocks, so the bwd ring re-runs the dq/dkv kernels per visiting
# block against the final (o, lse) residuals. dk/dv accumulators rotate
# WITH their blocks; after the last step one more hop lands each
# accumulator back on its home device.


def _lse_rows(lse128: jnp.ndarray) -> jnp.ndarray:
    return lse128[..., 0]                        # [b, nq, s]


def _merge_blocks(o, lse, o_i, lse_i):
    """Online merge of normalized per-block (o, lse) pairs, -inf-safe."""
    new = jnp.logaddexp(lse, lse_i)
    w = jnp.where(lse == NEG_INF, 0.0, jnp.exp(lse - new))
    w_i = jnp.where(lse_i == NEG_INF, 0.0, jnp.exp(lse_i - new))
    return o * w[..., None] + o_i * w_i[..., None], new


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q4, k4, v4, axis_name, causal, interpret):
    o4, _ = _ring_flash_fwd(q4, k4, v4, axis_name, causal, interpret)
    return o4


def _ring_flash_fwd(q4, k4, v4, axis_name, causal, interpret):
    from kubeflow_tpu.ops.pallas.flash_attention import flash_block_fwd

    size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, nq, s, hd = q4.shape
    o = jnp.zeros((b, nq, s, hd), jnp.float32)
    lse = jnp.full((b, nq, s), NEG_INF, jnp.float32)
    k_blk, v_blk = k4, v4
    for i in range(size):
        if i == 0:
            # the diagonal block: local causal masking (or full when the
            # whole attention is bidirectional)
            o_i, lse_i = flash_block_fwd(
                q4, k_blk, v_blk, causal=causal, interpret=interpret)
            o_i, lse_i = o_i.astype(jnp.float32), _lse_rows(lse_i)
        else:
            def attend(kv):
                oo, ll = flash_block_fwd(
                    q4, kv[0], kv[1], causal=False, interpret=interpret)
                return oo.astype(jnp.float32), _lse_rows(ll)

            def skip(kv):
                return (jnp.zeros((b, nq, s, hd), jnp.float32),
                        jnp.full((b, nq, s), NEG_INF, jnp.float32))

            if causal:
                # block i hops old = from device my-i: past iff my >= i
                o_i, lse_i = jax.lax.cond(
                    my >= i, attend, skip, (k_blk, v_blk))
            else:
                o_i, lse_i = attend((k_blk, v_blk))
        o, lse = _merge_blocks(o, lse, o_i, lse_i)
        if i + 1 < size:
            perm = [(d, (d + 1) % size) for d in range(size)]
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return o.astype(q4.dtype), lse


def _ring_flash_fwd_vjp(q4, k4, v4, axis_name, causal, interpret):
    o4, lse = _ring_flash_fwd(q4, k4, v4, axis_name, causal, interpret)
    return o4, (q4, k4, v4, o4, lse)


def _ring_flash_bwd(axis_name, causal, interpret, res, do4):
    from kubeflow_tpu.ops.pallas.flash_attention import flash_block_bwd

    q4, k4, v4, o4, lse = res
    size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, nq, s, hd = q4.shape
    nkv = k4.shape[1]
    lse128 = jnp.broadcast_to(lse[..., None], (*lse.shape, 128))

    dq = jnp.zeros((b, nq, s, hd), jnp.float32)
    dk_acc = jnp.zeros((b, nkv, s, hd), jnp.float32)
    dv_acc = jnp.zeros((b, nkv, s, hd), jnp.float32)
    k_blk, v_blk = k4, v4
    perm = [(d, (d + 1) % size) for d in range(size)]
    for i in range(size):
        if i == 0:
            dq_i, dk_i, dv_i = flash_block_bwd(
                (q4, k_blk, v_blk, o4, lse128), do4,
                causal=causal, interpret=interpret)
        else:
            def backprop(kv):
                a, bb, c = flash_block_bwd(
                    (q4, kv[0], kv[1], o4, lse128), do4,
                    causal=False, interpret=interpret)
                return (a.astype(jnp.float32), bb.astype(jnp.float32),
                        c.astype(jnp.float32))

            def skip(kv):
                return (jnp.zeros((b, nq, s, hd), jnp.float32),
                        jnp.zeros((b, nkv, s, hd), jnp.float32),
                        jnp.zeros((b, nkv, s, hd), jnp.float32))

            if causal:
                dq_i, dk_i, dv_i = jax.lax.cond(
                    my >= i, backprop, skip, (k_blk, v_blk))
            else:
                dq_i, dk_i, dv_i = backprop((k_blk, v_blk))
        dq = dq + dq_i.astype(jnp.float32)
        dk_acc = dk_acc + dk_i.astype(jnp.float32)
        dv_acc = dv_acc + dv_i.astype(jnp.float32)
        # Accumulators travel WITH their block; the rotation after the
        # final step is the hop that returns each accumulator home (the
        # K/V blocks themselves are dead after the last step — no hop).
        if i + 1 < size:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
    return (dq.astype(q4.dtype), dk_acc.astype(k4.dtype),
            dv_acc.astype(v4.dtype))


_ring_flash.defvjp(_ring_flash_fwd_vjp, _ring_flash_bwd)


def ring_flash_attention(
    q: jnp.ndarray,  # [b, s_local, n_q, hd]
    k: jnp.ndarray,  # [b, s_local, n_kv, hd]
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = True,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Ring attention with Pallas flash blocks. Call inside shard_map;
    same contract as `ring_attention` (global sequence = shard
    concatenation in axis order), differentiable via the ring-flash
    custom VJP. `interpret=None` auto-selects interpreter mode off-TPU."""
    if interpret is None:
        from kubeflow_tpu.ops.pallas.flash_attention import (
            _interpret_default)

        interpret = _interpret_default()
    q4 = jnp.transpose(q, (0, 2, 1, 3))
    k4 = jnp.transpose(k, (0, 2, 1, 3))
    v4 = jnp.transpose(v, (0, 2, 1, 3))
    o4 = _ring_flash(q4, k4, v4, axis_name, causal, interpret)
    return jnp.transpose(o4, (0, 2, 1, 3))


def ring_flash_attention_sharded(
    q: jnp.ndarray,  # [b, s_global, n_q, hd]
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    seq_axis: str = mesh_lib.FSDP_AXIS,
    causal: bool = True,
) -> jnp.ndarray:
    """shard_map wrapper for ring_flash_attention (see
    ring_attention_sharded for the layout contract)."""
    n = mesh.shape[seq_axis]
    if q.shape[1] % n != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by "
            f"{seq_axis}={n}"
        )
    spec = P(None, seq_axis, None, None)
    fn = mesh_lib.shard_map(
        functools.partial(ring_flash_attention, axis_name=seq_axis,
                          causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ulysses_attention(
    q: jnp.ndarray,  # [b, s_local, n_q, hd]
    k: jnp.ndarray,  # [b, s_local, n_kv, hd]
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = True,
    impl: str = "xla",
) -> jnp.ndarray:
    """Ulysses sequence parallelism. Call inside `shard_map`.

    all-to-all #1: [b, s/N, n, hd] -> [b, s, n/N, hd] (gather sequence,
    scatter heads); full attention on the now-complete sequence for the
    local head group; all-to-all #2 swaps back. Requires n_q and n_kv
    divisible by the axis size. The local attention is a COMPLETE
    causal attention over contiguous positions, so impl="flash" routes
    it straight through the Pallas kernel.
    """
    if impl not in ("xla", "flash"):
        raise ValueError(f"impl must be 'xla' or 'flash', got {impl!r}")
    size = jax.lax.psum(1, axis_name)
    n_q, n_kv = q.shape[2], k.shape[2]
    if n_q % size or n_kv % size:
        raise ValueError(
            f"ulysses needs heads divisible by axis size: "
            f"n_q={n_q} n_kv={n_kv} size={size}"
        )

    # split_axis=2 (heads), concat_axis=1 (sequence): tiled=True keeps the
    # array rank stable.
    def scatter_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def gather_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    b, s, nh, hd = qh.shape
    if impl == "flash":
        from kubeflow_tpu.ops.pallas.flash_attention import flash_attention

        return gather_heads(flash_attention(qh, kh, vh, causal=causal))
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    g = nh // kh.shape[2]
    qg = qh.reshape(b, s, kh.shape[2], g, hd)
    mask = (
        pos[:, :, None] >= pos[:, None, :]
        if causal
        else jnp.ones((b, s, s), dtype=bool)
    )
    o, m, l = _block_attend(qg, kh, vh, mask)
    out = (o / l[..., None]).reshape(b, s, nh, hd).astype(q.dtype)
    return gather_heads(out)


def ulysses_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    seq_axis: str = mesh_lib.FSDP_AXIS,
    causal: bool = True,
    impl: str = "xla",
) -> jnp.ndarray:
    """shard_map wrapper for `ulysses_attention` (see ring_attention_sharded)."""
    spec = P(None, seq_axis, None, None)
    fn = mesh_lib.shard_map(
        functools.partial(ulysses_attention, axis_name=seq_axis,
                          causal=causal, impl=impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
