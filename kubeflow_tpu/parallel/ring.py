"""Sequence/context parallelism: ring attention + Ulysses head-scatter.

Long-context attention over a sequence-sharded batch, the two TPU-idiomatic
layouts (SURVEY.md §5 "long-context"):

- **Ring attention** (`ring_attention`, `ring_attention_sharded`): each
  device keeps its Q shard resident and streams K/V shards around the ICI
  ring with `jax.lax.ppermute`, accumulating blockwise online-softmax
  partial results. O(s/N) activation memory per device, neighbor-only
  collectives (rides ICI links, never DCN). Explicit collectives via
  `shard_map` — this is deliberately NOT left to XLA: GSPMD would
  all-gather the full K/V.

- **Ulysses** (`ulysses_attention`): all-to-all swaps the sequence shard
  for a head shard, runs *full* local attention per head group, and swaps
  back. Cheaper when heads >= ring size and sequence fits after the swap;
  two all-to-alls instead of N-1 permutes.

Reference parity: the reference has no attention code of any kind
(SURVEY.md §2b row "SP/CP, ring attention"); this subsystem is green-field
TPU design.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.ops.attention import NEG_INF
from kubeflow_tpu.parallel import mesh as mesh_lib


def _block_attend(q, k, v, mask):
    """One blockwise-attention accumulation step (grouped-query, fp32).

    q: [b, sq, n_kv, g, hd]   (queries pre-grouped per kv head)
    k, v: [b, sk, n_kv, hd]
    mask: [b, sq, sk] bool (True = attend)
    Returns unnormalized (o, m, l) for online-softmax merging:
      o: [b, sq, n_kv, g, hd], m/l: [b, sq, n_kv, g]
    """
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bsngh,btnh->bngst", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                       # [b, n_kv, g, sq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bngst,btnh->bngsh", p, v.astype(jnp.float32))
    # rearrange to [b, sq, n_kv, g, ...] so seq leads like q/k/v
    perm = (0, 3, 1, 2)
    return (
        jnp.transpose(o, (0, 3, 1, 2, 4)),
        jnp.transpose(m, perm),
        jnp.transpose(l, perm),
    )


def ring_attention(
    q: jnp.ndarray,  # [b, s_local, n_q, hd]
    k: jnp.ndarray,  # [b, s_local, n_kv, hd]
    v: jnp.ndarray,  # [b, s_local, n_kv, hd]
    *,
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    """Ring attention over sequence shards. Call inside `shard_map`.

    The global sequence is the concatenation of per-device shards in
    axis-index order. K/V rotate one hop per step (N-1 ppermutes for an
    N-device ring) while each block's contribution merges into an
    online-softmax accumulator — numerically identical to full softmax
    attention over the gathered sequence.

    Causal masking is by *global* position, derived from the axis index of
    the device each K/V block originated on; fully-future blocks still
    execute (static schedule — no data-dependent control flow under jit)
    but contribute zero weight.
    """
    size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s, n_q, hd = q.shape
    n_kv = k.shape[2]
    assert n_q % n_kv == 0, (n_q, n_kv)
    g = n_q // n_kv
    qg = q.reshape(b, s, n_kv, g, hd)

    local_pos = jnp.arange(s, dtype=jnp.int32)
    q_pos = my_idx * s + local_pos                      # [s] global positions

    perm = [(i, (i + 1) % size) for i in range(size)]   # rotate k/v upward

    # Static unrolled ring (size is a compile-time constant under shard_map):
    # exactly size-1 ppermute hops — the last block needs no onward rotation.
    o = jnp.zeros((b, s, n_kv, g, hd), jnp.float32)
    m = jnp.full((b, s, n_kv, g), NEG_INF, jnp.float32)
    l = jnp.zeros((b, s, n_kv, g), jnp.float32)
    k_blk, v_blk = k, v
    for i in range(size):
        # Block i arrived after i hops: it originated on device my_idx - i.
        src = (my_idx - i) % size
        kv_pos = src * s + local_pos
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
        else:
            mask = jnp.ones((s, s), dtype=bool)
        mask = jnp.broadcast_to(mask, (b, s, s))
        o_i, m_i, l_i = _block_attend(qg, k_blk, v_blk, mask)
        m_new = jnp.maximum(m, m_i)
        a = jnp.exp(m - m_new)
        a_i = jnp.exp(m_i - m_new)
        o = o * a[..., None] + o_i * a_i[..., None]
        l = l * a + l_i * a_i
        m = m_new
        if i + 1 < size:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    # Causal guarantees every row attends at least to itself, so l > 0.
    out = o / l[..., None]
    return out.reshape(b, s, n_q, hd).astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,  # [b, s_global, n_q, hd]
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    seq_axis: str = mesh_lib.FSDP_AXIS,
    causal: bool = True,
) -> jnp.ndarray:
    """shard_map wrapper: sequence dim sharded over `seq_axis`, the rest
    replicated across it. Context parallelism conventionally reuses the
    fsdp device axis as the sequence axis (mesh.py axis convention)."""
    n = mesh.shape[seq_axis]
    if q.shape[1] % n != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by "
            f"{seq_axis}={n}"
        )
    spec = P(None, seq_axis, None, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ulysses_attention(
    q: jnp.ndarray,  # [b, s_local, n_q, hd]
    k: jnp.ndarray,  # [b, s_local, n_kv, hd]
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    """Ulysses sequence parallelism. Call inside `shard_map`.

    all-to-all #1: [b, s/N, n, hd] -> [b, s, n/N, hd] (gather sequence,
    scatter heads); full attention on the now-complete sequence for the
    local head group; all-to-all #2 swaps back. Requires n_q and n_kv
    divisible by the axis size.
    """
    size = jax.lax.psum(1, axis_name)
    n_q, n_kv = q.shape[2], k.shape[2]
    if n_q % size or n_kv % size:
        raise ValueError(
            f"ulysses needs heads divisible by axis size: "
            f"n_q={n_q} n_kv={n_kv} size={size}"
        )

    # split_axis=2 (heads), concat_axis=1 (sequence): tiled=True keeps the
    # array rank stable.
    def scatter_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def gather_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    b, s, nh, hd = qh.shape
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    g = nh // kh.shape[2]
    qg = qh.reshape(b, s, kh.shape[2], g, hd)
    mask = (
        pos[:, :, None] >= pos[:, None, :]
        if causal
        else jnp.ones((b, s, s), dtype=bool)
    )
    o, m, l = _block_attend(qg, kh, vh, mask)
    out = (o / l[..., None]).reshape(b, s, nh, hd).astype(q.dtype)
    return gather_heads(out)


def ulysses_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    seq_axis: str = mesh_lib.FSDP_AXIS,
    causal: bool = True,
) -> jnp.ndarray:
    """shard_map wrapper for `ulysses_attention` (see ring_attention_sharded)."""
    spec = P(None, seq_axis, None, None)
    fn = jax.shard_map(
        functools.partial(ulysses_attention, axis_name=seq_axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
