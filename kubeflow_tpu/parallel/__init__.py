"""Parallelism layer: meshes, sharding rules, and parallel transforms.

The reference control plane has no parallelism code (SURVEY.md §2b); this
package is the TPU-native value-add: jax.sharding Mesh construction from
slice topology, logical-axis sharding rules, and FSDP/TP/SP/EP strategies.
"""

from kubeflow_tpu.parallel.mesh import (
    MeshSpec,
    SliceTopology,
    SLICE_TOPOLOGIES,
    create_hybrid_mesh,
    create_mesh,
    get_abstract_mesh,
    mesh_from_env,
    num_slices_from_env,
    set_mesh,
)
from kubeflow_tpu.parallel.sharding import (
    ShardingRules,
    LLAMA_RULES,
    logical_to_spec,
    shard_pytree_specs,
    with_sharding_constraint,
)
from kubeflow_tpu.parallel.ring import (
    ring_attention,
    ring_attention_sharded,
    ring_flash_attention,
    ring_flash_attention_sharded,
    ulysses_attention,
    ulysses_attention_sharded,
)
# NOTE: the bare `pipeline` schedule fn is NOT re-exported — it would
# shadow the `kubeflow_tpu.parallel.pipeline` submodule name.
from kubeflow_tpu.parallel.pipeline import (
    pipeline_sharded,
    stack_stage_params,
)
from kubeflow_tpu.parallel.moe import (
    MoEConfig,
    init_moe,
    moe_logical_axes,
    moe_mlp,
    moe_mlp_expert_parallel,
    moe_mlp_sharded,
)
