"""Mixture-of-Experts with expert parallelism over ICI.

The reference has no MoE/parallelism code (SURVEY.md §2b row "Expert
parallelism (EP/MoE)": "pjit expert axis + ragged all-to-all over ICI").
This module supplies both TPU execution styles:

- `moe_mlp` — the GSPMD path: capacity-based top-k dispatch expressed as
  dense einsums. Under pjit with the experts dim sharded (logical axis
  "experts" → tensor), XLA partitions the expert computation and inserts
  the collectives itself. Zero hand-written communication; best when the
  expert dim is sharded over the same axis as the rest of the layer.

- `moe_mlp_expert_parallel` / `moe_mlp_sharded` — the explicit-EP path:
  `shard_map` over an expert axis; tokens are dispatched to the devices
  owning their experts with `jax.lax.all_to_all` (the TPU equivalent of
  the ragged a2a), computed, and returned. Deliberately explicit because
  GSPMD cannot infer the token→expert shuffle without materializing the
  full dispatch tensor on every device.

Routing is standard top-k softmax gating with per-expert capacity
(drop-overflow) and the Switch-style load-balancing auxiliary loss.
Everything is static-shaped: capacity is a compile-time constant, drops
are masked writes — no dynamic shapes under jit (XLA requirement).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    embed_dim: int = 512
    mlp_dim: int = 1024          # per-expert hidden dim (SwiGLU)
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32

    def capacity(self, n_tokens: int) -> int:
        """Per-expert token slots; static given a static token count."""
        cap = int(self.capacity_factor * n_tokens * self.top_k
                  / self.num_experts)
        return max(cap, self.top_k)


def init_moe(rng: jax.Array, cfg: MoEConfig) -> dict[str, jnp.ndarray]:
    kr, kg, ku, kd = jax.random.split(rng, 4)
    d, m, e = cfg.embed_dim, cfg.mlp_dim, cfg.num_experts
    s = d ** -0.5
    return {
        "router": (jax.random.normal(kr, (d, e)) * s).astype(cfg.dtype),
        "w_gate": (jax.random.normal(kg, (e, d, m)) * s).astype(cfg.dtype),
        "w_up": (jax.random.normal(ku, (e, d, m)) * s).astype(cfg.dtype),
        "w_down": (jax.random.normal(kd, (e, m, d)) * (m ** -0.5)).astype(cfg.dtype),
    }


def moe_logical_axes() -> dict[str, tuple[str | None, ...]]:
    """Logical axes for sharding.py rules ("experts" → tensor by default)."""
    return {
        "router": ("embed", None),
        "w_gate": ("experts", "embed", None),
        "w_up": ("experts", "embed", None),
        "w_down": ("experts", None, "embed"),
    }


def _route(router_logits: jnp.ndarray, cfg: MoEConfig, capacity: int):
    """Top-k routing with capacity. logits: [T, E] (fp32 recommended).

    Returns:
      dispatch: [T, E, C] one-hot bool — token t occupies slot c of expert e
      combine:  [T, E, C] float — dispatch weighted by router probability
      frac:     [E] fraction of routing choices per expert
      mean_prob:[E] mean router probability per expert
    (aux loss = E * sum(frac * mean_prob), Switch Transformer eq. 4-6 —
    returned as factors so sharded callers can average them over token
    shards BEFORE the product, keeping the loss identical to the
    single-device computation.)
    """
    T, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, cfg.top_k)   # [T, k]

    # Slot assignment: for the flattened (k, T) priority order, each
    # expert's tokens take consecutive slots. Rank-0 choices across all
    # tokens outrank rank-1 choices (Switch convention) so a token's
    # primary expert is dropped last.
    expert_onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # [T,k,E]
    prio = expert_onehot.transpose(1, 0, 2).reshape(cfg.top_k * T, E)
    pos_in_expert = jnp.cumsum(prio, axis=0) - prio               # [kT, E]
    pos = pos_in_expert.reshape(cfg.top_k, T, E).transpose(1, 0, 2)
    slot = jnp.sum(pos * expert_onehot, axis=-1)                  # [T, k]
    keep = slot < capacity

    combine = jnp.zeros((T, E, capacity), jnp.float32)
    disp = jnp.zeros((T, E, capacity), bool)
    t_idx = jnp.arange(T)[:, None].repeat(cfg.top_k, 1)
    safe_slot = jnp.where(keep, slot, 0)
    combine = combine.at[
        t_idx.ravel(), gate_idx.ravel(), safe_slot.ravel()
    ].add(jnp.where(keep, gate_vals, 0.0).ravel())
    disp = disp.at[
        t_idx.ravel(), gate_idx.ravel(), safe_slot.ravel()
    ].max(keep.ravel())

    frac = jnp.mean(
        jnp.sum(expert_onehot, axis=1).astype(jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    return disp, combine, frac, mean_prob


def _aux_loss(frac: jnp.ndarray, mean_prob: jnp.ndarray) -> jnp.ndarray:
    return frac.shape[0] * jnp.sum(frac * mean_prob)


def _expert_ffn(params, x_ecd: jnp.ndarray) -> jnp.ndarray:
    """Per-expert SwiGLU. x: [E, C, d] → [E, C, d]; E is a batched einsum
    dim so every expert's matmuls hit the MXU in one fused call."""
    gate = jnp.einsum("ecd,edm->ecm", x_ecd, params["w_gate"])
    up = jnp.einsum("ecd,edm->ecm", x_ecd, params["w_up"])
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up
    return jnp.einsum("ecm,emd->ecd", act, params["w_down"])


def moe_mlp(
    params: dict[str, jnp.ndarray],
    x: jnp.ndarray,            # [b, s, d]
    cfg: MoEConfig,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """GSPMD MoE layer: (output [b,s,d], aux loss). Shard params' experts
    dim via moe_logical_axes(); XLA inserts the collectives."""
    b, s, d = x.shape
    xt = x.reshape(b * s, d)
    capacity = cfg.capacity(b * s)
    logits = xt.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    disp, combine, frac, mean_prob = _route(logits, cfg, capacity)
    # [T,E,C] x [T,d] → [E,C,d]: the dispatch einsum
    xe = jnp.einsum("tec,td->ecd", disp.astype(x.dtype), xt)
    ye = _expert_ffn(params, xe)
    y = jnp.einsum("tec,ecd->td", combine.astype(ye.dtype), ye)
    return y.reshape(b, s, d).astype(x.dtype), _aux_loss(frac, mean_prob)


def moe_mlp_expert_parallel(
    params: dict[str, jnp.ndarray],   # experts dim LOCAL (E/N per device)
    x: jnp.ndarray,                   # [b_local, s, d] tokens LOCAL
    cfg: MoEConfig,
    *,
    axis_name: str,
    token_axes: tuple[str, ...] = (),
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Explicit expert parallelism. Call inside shard_map.

    `token_axes`: every mesh axis the token batch is sharded over
    (including `axis_name` when experts and tokens co-shard). The
    load-balance statistics are averaged over these axes *before* the
    frac·prob product, so the aux loss and its router gradient are
    bit-comparable to the unsharded `moe_mlp`.

    Capacity semantics (intended, GShard/Switch-style): capacity is
    derived from the LOCAL token count — each device grants every expert
    `capacity_factor * T_local * k / E` slots for its own tokens. Under
    tight capacity this drops per token-shard, not per global batch, so
    the same global batch can route differently on different mesh shapes
    and differs from `moe_mlp`'s global ranking. This is deliberate:
    exact global-drop parity would need a cross-device token ranking
    (a sort collective) before dispatch, defeating the point of EP. The
    per-shard semantics make each device's math identical to `moe_mlp`
    run on its local token block — tested that way in
    tests/test_moe.py::test_ep_tight_capacity_matches_per_shard_dense.

    Each device routes its local tokens against ALL experts (router
    weights replicated), builds capacity-bounded dispatch buffers, then a
    single `all_to_all` moves each expert-group's slots to the device
    owning those experts — the ragged all-to-all of SURVEY §2b, made
    rectangular by the capacity bound so shapes stay static. A second
    all_to_all returns expert outputs to the tokens' home devices.
    """
    n = jax.lax.psum(1, axis_name)
    b, s, d = x.shape
    T = b * s
    e_local = params["w_gate"].shape[0]
    E = e_local * n
    xt = x.reshape(T, d)
    capacity = cfg.capacity(T)

    logits = xt.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    disp, combine, frac, mean_prob = _route(logits, cfg, capacity)

    # Local dispatch buffers for every (global) expert: [E, C, d].
    xe = jnp.einsum("tec,td->ecd", disp.astype(x.dtype), xt)
    # a2a #1: split expert dim into N groups, concat along slots →
    # [E/N, N*C, d]: this device now holds ITS experts' slots from all
    # devices.
    xe = jax.lax.all_to_all(
        xe, axis_name, split_axis=0, concat_axis=1, tiled=True
    )
    ye = _expert_ffn(params, xe)
    # a2a #2 (inverse): [E/N, N*C, d] → [E, C, d] back on token owners.
    ye = jax.lax.all_to_all(
        ye, axis_name, split_axis=1, concat_axis=0, tiled=True
    )
    y = jnp.einsum("tec,ecd->td", combine.astype(ye.dtype), ye)
    # Average the statistics over every token-sharding axis FIRST, then
    # take the product — identical to the global single-device loss.
    for ax in (token_axes or (axis_name,)):
        frac = jax.lax.pmean(frac, ax)
        mean_prob = jax.lax.pmean(mean_prob, ax)
    return y.reshape(b, s, d).astype(x.dtype), _aux_loss(frac, mean_prob)


def moe_mlp_sharded(
    params: dict[str, jnp.ndarray],
    x: jnp.ndarray,               # [b, s, d] global
    cfg: MoEConfig,
    mesh: Mesh,
    *,
    expert_axis: str = mesh_lib.TENSOR_AXIS,
    batch_axes: tuple[str, ...] = (
        mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS, mesh_lib.TENSOR_AXIS,
    ),
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """shard_map wrapper: batch sharded over `batch_axes`, experts over
    `expert_axis` (EP reuses the tensor device axis per mesh.py).

    The expert axis is deliberately also a batch axis (the classic EP
    layout): tokens and experts shard along the same devices, so the
    all-to-alls move only the dispatched slots — no token replication.
    """
    n = mesh.shape[expert_axis]
    if cfg.num_experts % n:
        raise ValueError(
            f"num_experts={cfg.num_experts} not divisible by "
            f"{expert_axis}={n}"
        )
    n_batch = math.prod(mesh.shape[a] for a in batch_axes)
    if x.shape[0] % max(1, n_batch):
        raise ValueError(f"batch {x.shape[0]} not divisible by {batch_axes}")
    param_specs = {
        "router": P(),
        "w_gate": P(expert_axis),
        "w_up": P(expert_axis),
        "w_down": P(expert_axis),
    }
    x_spec = P(batch_axes, None, None)
    fn = mesh_lib.shard_map(
        functools.partial(
            moe_mlp_expert_parallel, cfg=cfg, axis_name=expert_axis,
            token_axes=tuple(batch_axes),
        ),
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=(x_spec, P()),
        check_vma=False,
    )
    return fn(params, x)
