"""Logical-axis sharding rules (t5x-style) mapped onto the mesh.

Every parameter/activation carries *logical* axis names ("embed", "heads",
"mlp", "batch", ...). A `ShardingRules` table maps logical names to mesh
axes; `logical_to_spec` resolves them into `PartitionSpec`s. Changing the
parallelism strategy (FSDP vs TP vs both) is a rules change, not a model
change — this is the TPU-idiomatic answer to the reference's absent
parallelism layer (SURVEY.md §2b).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Maps logical axis names to mesh axis names (or None = replicated)."""

    rules: Mapping[str, str | tuple[str, ...] | None]

    def resolve(self, logical_axes: tuple[str | None, ...]) -> P:
        out: list[Any] = []
        for ax in logical_axes:
            if ax is None:
                out.append(None)
            else:
                if ax not in self.rules:
                    raise KeyError(f"no sharding rule for logical axis {ax!r}")
                out.append(self.rules[ax])
        # Trailing Nones can be dropped but keeping them is harmless.
        return P(*out)


# The canonical Llama/transformer rule set. Params and activations use
# DISTINCT logical names: a param's embed dim shards over fsdp (ZeRO-3 —
# gathered per-layer), while an activation's embed dim stays unsharded
# (its batch dim already carries data×fsdp); TP shards params' and
# activations' heads/mlp/vocab dims over tensor.
LLAMA_RULES = ShardingRules(
    rules={
        # --- params ---
        "embed": mesh_lib.FSDP_AXIS,
        "heads": mesh_lib.TENSOR_AXIS,
        "kv_heads": mesh_lib.TENSOR_AXIS,
        "head_dim": None,
        "mlp": mesh_lib.TENSOR_AXIS,
        "vocab": mesh_lib.TENSOR_AXIS,
        "layers": None,
        "experts": mesh_lib.TENSOR_AXIS,
        "stage": None,
        "lora_rank": None,  # rank dim is tiny — always replicated
        # --- activations ---
        # dcn leads: on hybrid multi-slice meshes the batch's outermost
        # split is across slices (pure DP over DCN); single-slice meshes
        # have no dcn axis and _filter_spec_to_mesh drops it.
        "batch": (mesh_lib.DCN_AXIS, mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS),
        "seq": None,
        "act_embed": None,
        "act_heads": mesh_lib.TENSOR_AXIS,
        "act_kv_heads": mesh_lib.TENSOR_AXIS,
        "act_mlp": mesh_lib.TENSOR_AXIS,
        "act_vocab": mesh_lib.TENSOR_AXIS,
    }
)


def logical_to_spec(rules: ShardingRules, logical: Any) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: rules.resolve(axes),
        logical,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(a is None or isinstance(a, str) for a in x),
    )


def shard_pytree_specs(rules: ShardingRules, logical: Any, mesh: Mesh) -> Any:
    """Like logical_to_spec but returns NamedShardings bound to `mesh`."""
    specs = logical_to_spec(rules, logical)
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _filter_spec_to_mesh(spec: P) -> P:
    """Drop mesh axes the current context can't constrain.

    Model code names logical axes unconditionally; which physical axes
    exist — and which are already manual because we're inside a
    shard_map (e.g. the PP stage axis) — depends on the caller's mesh.
    Axes missing from the mesh or not Auto are unconstrainable there by
    definition, so dropping them is the correct meaning of the
    constraint, not a silent loss (typos are still caught earlier by
    rules.resolve on the LOGICAL name)."""
    mesh = mesh_lib.get_abstract_mesh()
    if mesh is None:
        return spec  # no mesh context; with_sharding_constraint will no-op
    axis_types = getattr(mesh, "axis_types", None)
    axis_type_cls = getattr(jax.sharding, "AxisType", None)
    if axis_types and axis_type_cls is not None:
        auto = {
            name
            for name, t in zip(mesh.axis_names, axis_types)
            if t == axis_type_cls.Auto
        }
    else:
        # legacy global-mesh context (pre-AxisType jax): every axis is
        # auto-sharded, so only filter axes absent from the mesh
        auto = set(mesh.axis_names)

    def filt(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in auto)
            return kept if kept else None
        return entry if entry in auto else None

    return P(*(filt(e) for e in spec))


def with_sharding_constraint(x: Any, logical_axes: tuple[str | None, ...],
                             rules: ShardingRules = LLAMA_RULES) -> Any:
    """Constrain an activation's sharding by logical axes (no-op outside jit
    without a mesh context)."""
    spec = rules.resolve(logical_axes)  # typos in logical names must raise
    spec = _filter_spec_to_mesh(spec)
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except Exception as e:
        # Only the no-mesh-context case is advisory (plain eager CPU runs).
        # Anything else — unknown mesh axis, duplicate axes in one spec —
        # is a real sharding bug and must surface. (A broad "mesh" match
        # would swallow "Resource axis ... not found in mesh" too.)
        msg = str(e).lower()
        if "empty mesh" in msg or "mesh context" in msg or "requires a mesh" in msg:
            return x
        raise
