"""Logical-axis sharding rules (t5x-style) mapped onto the mesh.

Every parameter/activation carries *logical* axis names ("embed", "heads",
"mlp", "batch", ...). A `ShardingRules` table maps logical names to mesh
axes; `logical_to_spec` resolves them into `PartitionSpec`s. Changing the
parallelism strategy (FSDP vs TP vs both) is a rules change, not a model
change — this is the TPU-idiomatic answer to the reference's absent
parallelism layer (SURVEY.md §2b).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Maps logical axis names to mesh axis names (or None = replicated)."""

    rules: Mapping[str, str | tuple[str, ...] | None]

    def resolve(self, logical_axes: tuple[str | None, ...]) -> P:
        out: list[Any] = []
        for ax in logical_axes:
            if ax is None:
                out.append(None)
            else:
                if ax not in self.rules:
                    raise KeyError(f"no sharding rule for logical axis {ax!r}")
                out.append(self.rules[ax])
        # Trailing Nones can be dropped but keeping them is harmless.
        return P(*out)


# The canonical Llama/transformer rule set. Params and activations use
# DISTINCT logical names: a param's embed dim shards over fsdp (ZeRO-3 —
# gathered per-layer), while an activation's embed dim stays unsharded
# (its batch dim already carries data×fsdp); TP shards params' and
# activations' heads/mlp/vocab dims over tensor.
LLAMA_RULES = ShardingRules(
    rules={
        # --- params ---
        "embed": mesh_lib.FSDP_AXIS,
        "heads": mesh_lib.TENSOR_AXIS,
        "kv_heads": mesh_lib.TENSOR_AXIS,
        "head_dim": None,
        "mlp": mesh_lib.TENSOR_AXIS,
        "vocab": mesh_lib.TENSOR_AXIS,
        "layers": None,
        "experts": mesh_lib.TENSOR_AXIS,
        "stage": None,
        "lora_rank": None,  # rank dim is tiny — always replicated
        # --- activations ---
        # dcn leads: on hybrid multi-slice meshes the batch's outermost
        # split is across slices (pure DP over DCN); single-slice meshes
        # have no dcn axis and _filter_spec_to_mesh drops it.
        "batch": (mesh_lib.DCN_AXIS, mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS),
        "seq": None,
        "act_embed": None,
        "act_heads": mesh_lib.TENSOR_AXIS,
        "act_kv_heads": mesh_lib.TENSOR_AXIS,
        "act_mlp": mesh_lib.TENSOR_AXIS,
        "act_vocab": mesh_lib.TENSOR_AXIS,
    }
)


def logical_to_spec(rules: ShardingRules, logical: Any) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: rules.resolve(axes),
        logical,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(a is None or isinstance(a, str) for a in x),
    )


def shard_pytree_specs(rules: ShardingRules, logical: Any, mesh: Mesh) -> Any:
    """Like logical_to_spec but returns NamedShardings bound to `mesh`."""
    specs = logical_to_spec(rules, logical)
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _spec_axes(spec: P) -> set[str]:
    """All mesh axis names a PartitionSpec already consumes."""
    used: set[str] = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def zero_extend_spec(spec: P, shape: tuple[int, ...], mesh: Mesh,
                     axis: str = mesh_lib.DATA_AXIS) -> P:
    """Fold `axis` (default "data") into `spec`, ZeRO-style.

    Optimizer moments normally mirror their parameter's sharding, which
    leaves them REPLICATED over the data axis — every data-parallel
    replica holds a full copy. ZeRO partitions that redundancy away:
    extend the spec so the first dimension that (a) is divisible by the
    axis size after any existing sharding and (b) doesn't already use
    the axis, is additionally split over `axis`. XLA then materializes
    the update as reduce-scatter(grads) + sharded-update + all-gather
    (params) instead of an all-reduce plus N redundant updates.

    Returns `spec` unchanged when the axis is absent/size-1, already
    used, or no dimension divides — so data=1 meshes (all existing
    tests) are exact no-ops.
    """
    if axis not in mesh.axis_names:
        return spec
    axis_size = mesh.shape[axis]
    if axis_size <= 1 or axis in _spec_axes(spec):
        return spec
    entries: list[Any] = list(spec) + [None] * (len(shape) - len(spec))
    for i, dim in enumerate(shape):
        entry = entries[i]
        if entry is None:
            existing: tuple[str, ...] = ()
        elif isinstance(entry, (tuple, list)):
            existing = tuple(entry)
        else:
            existing = (entry,)
        sharded_by = 1
        for name in existing:
            sharded_by *= mesh.shape.get(name, 1)
        per_shard = dim // sharded_by if sharded_by and dim % sharded_by == 0 else 0
        if per_shard and per_shard % axis_size == 0:
            entries[i] = existing + (axis,) if existing else axis
            return P(*entries)
    return spec  # nothing divides (scalars, tiny leaves) — stay mirrored


def zero_extend_sharding(sharding: NamedSharding, shape: tuple[int, ...],
                         axis: str = mesh_lib.DATA_AXIS) -> NamedSharding:
    """NamedSharding-level zero_extend_spec (same mesh, extended spec)."""
    spec = zero_extend_spec(sharding.spec, shape, sharding.mesh, axis)
    return NamedSharding(sharding.mesh, spec)


def make_shard_and_gather_fns(shardings: Any):
    """Per-leaf (shard_fns, gather_fns) for a pytree of NamedShardings.

    shard_fns place a host/numpy leaf onto the mesh under its spec;
    gather_fns pull a (possibly sharded) leaf back to a host array.
    This is the checkpoint-resize bridge: gather under the OLD mesh,
    shard under the NEW one — the two meshes never need to coexist
    inside a single jit.
    """
    is_leaf = lambda x: isinstance(x, NamedSharding)  # noqa: E731

    def make_shard(s: NamedSharding):
        return lambda x: jax.device_put(x, s)

    def make_gather(_s: NamedSharding):
        return lambda x: jax.device_get(x)

    return (
        jax.tree.map(make_shard, shardings, is_leaf=is_leaf),
        jax.tree.map(make_gather, shardings, is_leaf=is_leaf),
    )


def _filter_spec_to_mesh(spec: P) -> P:
    """Drop mesh axes the current context can't constrain.

    Model code names logical axes unconditionally; which physical axes
    exist — and which are already manual because we're inside a
    shard_map (e.g. the PP stage axis) — depends on the caller's mesh.
    Axes missing from the mesh or not Auto are unconstrainable there by
    definition, so dropping them is the correct meaning of the
    constraint, not a silent loss (typos are still caught earlier by
    rules.resolve on the LOGICAL name)."""
    mesh = mesh_lib.get_abstract_mesh()
    if mesh is None:
        return spec  # no mesh context; with_sharding_constraint will no-op
    axis_types = getattr(mesh, "axis_types", None)
    axis_type_cls = getattr(jax.sharding, "AxisType", None)
    if axis_types and axis_type_cls is not None:
        auto = {
            name
            for name, t in zip(mesh.axis_names, axis_types)
            if t == axis_type_cls.Auto
        }
    else:
        # legacy global-mesh context (pre-AxisType jax): every axis is
        # auto-sharded, so only filter axes absent from the mesh
        auto = set(mesh.axis_names)

    def filt(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in auto)
            return kept if kept else None
        return entry if entry in auto else None

    return P(*(filt(e) for e in spec))


def with_sharding_constraint(x: Any, logical_axes: tuple[str | None, ...],
                             rules: ShardingRules = LLAMA_RULES) -> Any:
    """Constrain an activation's sharding by logical axes (no-op outside jit
    without a mesh context)."""
    spec = rules.resolve(logical_axes)  # typos in logical names must raise
    spec = _filter_spec_to_mesh(spec)
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except Exception as e:
        # Only the no-mesh-context case is advisory (plain eager CPU runs).
        # Anything else — unknown mesh axis, duplicate axes in one spec —
        # is a real sharding bug and must surface. (A broad "mesh" match
        # would swallow "Resource axis ... not found in mesh" too.)
        msg = str(e).lower()
        if "empty mesh" in msg or "mesh context" in msg or "requires a mesh" in msg:
            return x
        raise
