"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

SURVEY.md §2b row "Pipeline parallelism (PP)": the reference has none; the
TPU-native equivalent is stage partitioning with activations flowing over
ICI/DCN neighbor links. Design:

- Per-stage params are STACKED on a leading stage dim and sharded over the
  stage axis — each device holds exactly its stage's weights (like the
  stacked-layer scan in the Llama model, but across devices).
- The schedule is a single `lax.scan` over M + S - 1 ticks. At tick t,
  stage s computes microbatch t - s; boundary activations move one hop
  per tick with `jax.lax.ppermute` (neighbor-only: rides ICI within a
  slice, DCN between slices — never an all-gather).
- Everything is static-shaped; inactive (bubble) ticks compute on zeros
  and mask their writes. That wastes the bubble FLOPs (standard GPipe
  cost, S-1 of M+S-1 ticks) but keeps XLA's schedule fully static.

The transformation is differentiable (scan + ppermute have VJPs), so the
same code path trains — grads for each stage's params stay resident on
that stage's device.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.parallel import mesh as mesh_lib


def pipeline(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,        # this device's stage params (leading dim dropped)
    x_mb: jnp.ndarray,        # [M, mb, ...] microbatches (replicated input)
    *,
    axis_name: str,
) -> jnp.ndarray:
    """Run the pipeline schedule. Call inside shard_map.

    `stage_fn(params, x) -> y` must map activations to same-shaped
    activations (the classic homogeneous-stage constraint; embed/unembed
    belong inside the first/last stage_fn via lax.cond on the stage index
    or — simpler — as pre/post transforms outside the pipeline).

    Returns [M, mb, ...] outputs, replicated across the stage axis.
    """
    S = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    M = x_mb.shape[0]
    act_shape = x_mb.shape[1:]
    total = M + S - 1

    fwd_perm = [(i, i + 1) for i in range(S - 1)]

    def tick(carry, t):
        prev_act, outs = carry
        mb_idx = t - idx
        active = (mb_idx >= 0) & (mb_idx < M)
        # Stage 0 pulls a fresh microbatch; later stages consume the
        # activation handed over the ring on the previous tick.
        fresh = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        inp = jnp.where(idx == 0, fresh, prev_act)
        out = stage_fn(stage_params, inp)
        out = jnp.where(active, out, jnp.zeros_like(out))
        # Last stage deposits its finished microbatch.
        write = jnp.where(
            (idx == S - 1) & active, out, jnp.zeros_like(out)
        )
        outs = jax.lax.dynamic_update_index_in_dim(
            outs,
            jax.lax.dynamic_index_in_dim(
                outs, jnp.clip(mb_idx, 0, M - 1), axis=0, keepdims=False
            ) + write,
            jnp.clip(mb_idx, 0, M - 1),
            axis=0,
        )
        # Hand the activation to the next stage (stage S-1 sends nowhere).
        nxt = jax.lax.ppermute(out, axis_name, fwd_perm) if S > 1 else out
        return (nxt, outs), None

    outs0 = jnp.zeros((M, *act_shape), x_mb.dtype)
    act0 = jnp.zeros(act_shape, x_mb.dtype)
    (_, outs), _ = jax.lax.scan(
        tick, (act0, outs0), jnp.arange(total, dtype=jnp.int32)
    )
    # Results live on the last stage only; share them ring-wide so every
    # stage returns the same replicated output (psum of one-hot deposits).
    return jax.lax.psum(outs, axis_name)


def pipeline_sharded(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stacked_params: Any,      # leaves [S, ...] — stage-major stacked
    x: jnp.ndarray,           # [batch, ...] global batch
    mesh: Mesh,
    *,
    stage_axis: str,
    num_microbatches: int,
) -> jnp.ndarray:
    """shard_map wrapper: split batch into microbatches, shard stacked
    params over `stage_axis`, run the schedule, return [batch, ...].

    The stage axis is whichever mesh axis the caller dedicates to PP
    (inter-slice DCN meshes typically use the outermost axis so stage
    hops are the only cross-slice traffic).
    """
    S = mesh.shape[stage_axis]
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by microbatches {num_microbatches}"
        )
    leaves = jax.tree.leaves(stacked_params)
    if any(leaf.shape[0] != S for leaf in leaves):
        raise ValueError(
            f"stacked params' leading dim must equal {stage_axis}={S}, "
            f"got {sorted({leaf.shape[0] for leaf in leaves})}"
        )
    x_mb = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])

    def local(params_stacked_local, x_rep):
        # shard_map hands each device a [1, ...] slice; drop the dim.
        params_local = jax.tree.map(
            lambda leaf: jnp.squeeze(leaf, axis=0), params_stacked_local
        )
        return pipeline(stage_fn, params_local, x_rep, axis_name=stage_axis)

    param_specs = jax.tree.map(lambda _: P(stage_axis), stacked_params)
    # Manual collectives only over the stage axis; any other mesh axes
    # (data, fsdp, ...) stay automatic, so GSPMD keeps handling their
    # sharding — and their gradient reductions — inside the stage loop.
    # This is what lets PP compose with a (stage, data) mesh and the real
    # Trainer optimizer without hand-written data-parallel psums.
    fn = mesh_lib.shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        axis_names={stage_axis},
        check_vma=False,
    )
    y_mb = fn(stacked_params, x_mb)
    return y_mb.reshape(b, *y_mb.shape[2:])


def stack_stage_params(per_stage: list[Any]) -> Any:
    """[stage0_tree, stage1_tree, ...] → one tree with leading stage dim."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage)


def pipeline_spec_rules() -> dict[str, str]:
    """Logical-axis additions for sharding.py rule tables ("stage")."""
    return {"stage": "stage"}


def reference_forward(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    per_stage_params: list[Any],
    x: jnp.ndarray,
) -> jnp.ndarray:
    """Sequential stage composition — the numerics oracle for tests."""
    for p in per_stage_params:
        x = stage_fn(p, x)
    return x
