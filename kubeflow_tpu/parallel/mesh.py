"""Device-mesh construction from TPU slice topologies.

The control plane places notebook/training pods on TPU slices and injects
topology env (see kubeflow_tpu.controlplane.webhook); this module is the
compute-side consumer: it turns a slice topology (e.g. "v5e-16") plus a
parallelism layout into a `jax.sharding.Mesh` whose collectives ride ICI.

Reference parity: the reference has zero mesh/parallelism code
(SURVEY.md §2b); its closest hook is topology-aware placement
(tensorboard_controller.go:408-451). Here the topology becomes a first-class
object so both the control plane (placement, replica counts) and JAX
(mesh axes) read from the same source of truth.

Axis convention (outer → inner, slowest-varying → fastest):
  "data"   — pure data parallelism, gradients all-reduced (DCN-friendly)
  "fsdp"   — sharded data parallelism: params/optimizer sharded, gathered
             per-layer (ZeRO-3 style, ICI all-gather/reduce-scatter)
  "tensor" — tensor (Megatron-style) parallelism inside a layer
Sequence ("seq") and expert ("expert") axes are introduced by the
ring-attention / MoE transforms in kubeflow_tpu.parallel, reusing these
same device axes via mesh reshaping rather than separate physical axes.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "tensor"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
STAGE_AXIS = "stage"
DCN_AXIS = "dcn"

MESH_AXES = (DATA_AXIS, FSDP_AXIS, TENSOR_AXIS)
HYBRID_MESH_AXES = (DCN_AXIS,) + MESH_AXES

NUM_SLICES_ENV = "KFTPU_NUM_SLICES"
MEGASCALE_NUM_SLICES_ENV = "MEGASCALE_NUM_SLICES"


def set_mesh(mesh: Mesh):
    """Context manager installing `mesh` as the ambient mesh.
    `jax.set_mesh` where it exists; on older jax the Mesh object is
    itself the (legacy global-mesh) context manager with the same
    scoping behavior for jit + sharding constraints."""
    fn = getattr(jax, "set_mesh", None)
    return fn(mesh) if fn is not None else mesh


def get_abstract_mesh():
    """The current ambient mesh, or None when there is no usable mesh
    context. jax only exports `jax.sharding.get_abstract_mesh` publicly
    from 0.5; on older versions the equivalent scope is the legacy
    global-mesh context (what set_mesh above installs there), read from
    thread_resources. Callers must treat None as "trivial mesh"
    (gather / no-constraint paths)."""
    fn = getattr(jax.sharding, "get_abstract_mesh", None)
    if fn is not None:
        mesh = fn()
        return mesh if getattr(mesh, "axis_names", ()) else None
    try:
        from jax._src.mesh import thread_resources
        mesh = thread_resources.env.physical_mesh
    except Exception:  # noqa: BLE001 — private layout changed; no mesh
        return None
    return mesh if getattr(mesh, "axis_names", ()) else None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
              axis_names=None):
    """`jax.shard_map` with the modern keyword surface, bridged to
    `jax.experimental.shard_map` on older jax: `check_vma` maps to
    `check_rep`, and `axis_names` (the manual axes) maps to its
    complement `auto` (the axes left to the partitioner)."""
    fn = getattr(jax, "shard_map", None)
    kwargs = {}
    if fn is not None:
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)
    from jax.experimental.shard_map import shard_map as legacy_shard_map
    if check_vma is not None:
        kwargs["check_rep"] = check_vma
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    return legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, **kwargs)


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """A TPU slice: chip grid plus host layout.

    `hosts` is the number of TPU VM hosts (pods the controller must gang-
    schedule; each host sees `chips_per_host` local chips). This is what
    the notebook controller uses for StatefulSet replica counts and what
    the webhook uses to build TPU_WORKER_HOSTNAMES.
    """

    name: str           # e.g. "v5e-16"
    generation: str     # "v5e", "v5p", "v4", ...
    chips: int          # total chips in the slice
    grid: tuple[int, ...]  # physical ICI grid, e.g. (4, 4)
    chips_per_host: int    # chips visible to one TPU VM host

    @property
    def hosts(self) -> int:
        return max(1, self.chips // self.chips_per_host)


def _v5e(n: int, grid: tuple[int, ...]) -> SliceTopology:
    # v5e: 1,4 or 8 chips/host depending on slice; 4 for multi-host slices,
    # n for single-host slices up to 8.
    cph = n if n <= 8 else 4
    return SliceTopology(f"v5e-{n}", "v5e", n, grid, cph)


def _v5p(n: int, grid: tuple[int, ...]) -> SliceTopology:
    return SliceTopology(f"v5p-{n}", "v5p", n, grid, min(n, 4))


def _v4(n: int, grid: tuple[int, ...]) -> SliceTopology:
    return SliceTopology(f"v4-{n}", "v4", n, grid, min(n, 4))


SLICE_TOPOLOGIES: dict[str, SliceTopology] = {
    t.name: t
    for t in [
        _v5e(1, (1, 1)),
        _v5e(4, (2, 2)),
        _v5e(8, (2, 4)),
        _v5e(16, (4, 4)),
        _v5e(32, (4, 8)),
        _v5e(64, (8, 8)),
        _v5e(128, (8, 16)),
        _v5e(256, (16, 16)),
        _v5p(8, (2, 2, 1)),
        _v5p(16, (2, 2, 2)),
        _v5p(32, (2, 2, 4)),
        _v5p(128, (4, 4, 4)),
        _v4(8, (2, 2, 1)),
        _v4(16, (2, 2, 2)),
        _v4(32, (2, 2, 4)),
    ]
}


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A parallelism layout over a device set.

    Sizes of -1 mean "absorb the remaining devices" (at most one axis may
    be -1). The product of resolved sizes must equal the device count.
    """

    data: int = 1
    fsdp: int = -1
    tensor: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {DATA_AXIS: self.data, FSDP_AXIS: self.fsdp, TENSOR_AXIS: self.tensor}
        free = [k for k, v in sizes.items() if v == -1]
        if len(free) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {free}")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if free:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[free[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} does not cover {n_devices} devices"
            )
        return sizes


def create_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    topology: str | SliceTopology | None = None,
) -> Mesh:
    """Build a Mesh with (data, fsdp, tensor) axes over the given devices.

    JAX device order on TPU already follows the physical ICI grid; keeping
    the innermost mesh axes innermost therefore maps their collectives onto
    ICI neighbor links. When `topology` names a known slice it is used for
    validation: a device count that matches neither the slice's chips nor
    a CPU simulation is rejected so a control-plane/topology mismatch fails
    here instead of producing a silently wrong mesh.
    """
    if devices is None:
        devices = jax.devices()
    spec = spec or MeshSpec()
    if isinstance(topology, str):
        topology = SLICE_TOPOLOGIES[topology]
    if topology is not None:
        backend = getattr(devices[0], "platform", jax.default_backend())
        if backend == "tpu" and len(devices) != topology.chips:
            raise ValueError(
                f"topology {topology.name} has {topology.chips} chips but "
                f"{len(devices)} TPU devices are visible — control-plane "
                "topology env and actual slice disagree"
            )
        if backend != "tpu" and len(devices) != topology.chips:
            logging.getLogger(__name__).warning(
                "simulating topology %s (%d chips) with %d %s devices",
                topology.name, topology.chips, len(devices), backend,
            )
    sizes = spec.resolve(len(devices))
    dev_array = np.asarray(devices).reshape(
        sizes[DATA_AXIS], sizes[FSDP_AXIS], sizes[TENSOR_AXIS]
    )
    return Mesh(dev_array, MESH_AXES)


def create_hybrid_mesh(
    spec: MeshSpec | None = None,
    *,
    num_slices: int,
    devices: Sequence[jax.Device] | None = None,
    topology: str | SliceTopology | None = None,
) -> Mesh:
    """Hybrid multi-slice mesh: ("dcn", "data", "fsdp", "tensor").

    The outer `dcn` axis spans TPU slices; collectives over it ride the
    data-center network, everything inner rides ICI. The scaling-book
    recipe for >1-slice jobs: keep bandwidth-hungry sharding (fsdp/
    tensor) inside a slice, put pure data parallelism — one gradient
    all-reduce per step — across slices. Params carry no `dcn` rule
    (parallel.sharding.LLAMA_RULES), so they replicate per-slice and
    only grads cross DCN.

    Slice membership comes from `device.slice_index` when the runtime
    exposes it (real multi-slice jobs); simulated/virtual device sets
    fall back to contiguous equal chunks, which matches how
    `xla_force_host_platform_device_count` lays out virtual devices.
    `spec` describes the layout WITHIN one slice.
    """
    if devices is None:
        devices = jax.devices()
    if num_slices < 1:
        raise ValueError(f"num_slices must be >= 1, got {num_slices}")
    if len(devices) % num_slices:
        raise ValueError(
            f"{len(devices)} devices not divisible into {num_slices} slices"
        )
    per_slice = len(devices) // num_slices

    by_slice: dict[int, list[jax.Device]] = {}
    groups: list[list[jax.Device]] | None = None
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        for d in devices:
            by_slice.setdefault(d.slice_index, []).append(d)
        if len(by_slice) == num_slices and all(
            len(g) == per_slice for g in by_slice.values()
        ):
            groups = [by_slice[k] for k in sorted(by_slice)]
        elif getattr(devices[0], "platform",
                     jax.default_backend()) == "tpu":
            # Real hardware disagreeing with the control plane must
            # fail here, not build a mesh whose "cross-slice" axis
            # doesn't actually cross slices.
            raise ValueError(
                f"device slice_index grouping "
                f"{sorted((k, len(v)) for k, v in by_slice.items())} "
                f"does not match num_slices={num_slices} x {per_slice}"
            )
        else:
            # Virtual CPU devices carry slice_index=0 across ALL
            # processes (observed in the 4-process hybrid gang test) —
            # the attribute exists but is meaningless off-TPU, so fall
            # through to contiguous chunks, which matches both
            # xla_force_host_platform_device_count layout and
            # process-ordinal ordering in multi-process groups.
            logging.getLogger(__name__).warning(
                "ignoring non-TPU slice_index grouping %s; using "
                "contiguous %d-device chunks",
                sorted((k, len(v)) for k, v in by_slice.items()),
                per_slice,
            )
    if groups is None:
        groups = [
            list(devices[i * per_slice:(i + 1) * per_slice])
            for i in range(num_slices)
        ]

    spec = spec or MeshSpec()
    if isinstance(topology, str):
        topology = SLICE_TOPOLOGIES[topology]
    if topology is not None and per_slice != topology.chips:
        # Same rule as create_mesh: on real TPU a control-plane/slice
        # disagreement must fail here, not build a silently wrong mesh;
        # only CPU/virtual simulations downgrade to a warning.
        backend = getattr(devices[0], "platform", jax.default_backend())
        if backend == "tpu":
            raise ValueError(
                f"topology {topology.name} has {topology.chips} chips "
                f"per slice but {per_slice} TPU devices per slice are "
                "visible — control-plane topology env and actual "
                "slices disagree"
            )
        logging.getLogger(__name__).warning(
            "simulating %d-slice %s (%d chips each) with %d devices/slice",
            num_slices, topology.name, topology.chips, per_slice,
        )
    sizes = spec.resolve(per_slice)
    dev_array = np.stack([
        np.asarray(g).reshape(
            sizes[DATA_AXIS], sizes[FSDP_AXIS], sizes[TENSOR_AXIS]
        )
        for g in groups
    ])
    return Mesh(dev_array, HYBRID_MESH_AXES)


def num_slices_from_env() -> int:
    """Slice count injected by the webhook (KFTPU_NUM_SLICES, mirroring
    MEGASCALE_NUM_SLICES); 1 when absent."""
    for var in (NUM_SLICES_ENV, MEGASCALE_NUM_SLICES_ENV):
        raw = os.environ.get(var, "")
        if raw:
            try:
                return max(1, int(raw))
            except ValueError:
                raise ValueError(f"malformed {var}={raw!r}") from None
    return 1


def mesh_from_env(devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a mesh from control-plane-injected env.

    The webhook injects KFTPU_MESH="data=1,fsdp=16,tensor=1" (and the
    topology via KFTPU_TOPOLOGY). Falls back to pure-FSDP over all devices.
    Multi-slice gangs (KFTPU_NUM_SLICES > 1) get the hybrid mesh with the
    extra outer "dcn" axis; KFTPU_MESH then describes one slice's layout.
    """
    raw = os.environ.get("KFTPU_MESH", "")
    kwargs: dict[str, int] = {}
    if raw:
        for part in raw.split(","):
            k, _, v = part.partition("=")
            k = k.strip()
            if k in (DATA_AXIS, FSDP_AXIS, TENSOR_AXIS):
                try:
                    kwargs[k] = int(v)
                except ValueError:
                    raise ValueError(
                        f"malformed KFTPU_MESH entry {part!r} "
                        f"(full value: {raw!r})"
                    ) from None
    spec = MeshSpec(**kwargs) if kwargs else MeshSpec()
    topo = os.environ.get("KFTPU_TOPOLOGY") or None
    if topo is not None and topo not in SLICE_TOPOLOGIES:
        # Control plane injected a topology this library build doesn't
        # know — proceed without topology validation but say so.
        logging.getLogger(__name__).warning(
            "unknown KFTPU_TOPOLOGY %r (known: %s); skipping slice "
            "validation", topo, sorted(SLICE_TOPOLOGIES),
        )
        topo = None
    n_slices = num_slices_from_env()
    if n_slices > 1:
        return create_hybrid_mesh(
            spec, num_slices=n_slices, devices=devices, topology=topo
        )
    return create_mesh(spec, devices=devices, topology=topo)
