"""Hot-reloaded config files (the reference's fsnotify mechanism).

The reference watches mounted ConfigMaps and reloads without restart:
profile default namespace labels via fsnotify with symlink-aware re-add
(profile_controller.go:356-405, 743-758), JWA spawner config re-read per
request (jupyter utils.py:22-53). Kubernetes swaps an entire symlinked
directory on ConfigMap update, so inotify on the file itself goes stale —
the reference re-adds its watch; we poll the resolved real path + mtime
(hermetic, no OS-specific watch API) and invoke callbacks on change.

`WatchedConfig.data` is replaced atomically (readers grab the attribute);
callbacks run on the poller thread.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Callable

log = logging.getLogger(__name__)


def _parse(path: str, raw: str) -> Any:
    if path.endswith((".yaml", ".yml")):
        import yaml

        return yaml.safe_load(raw)
    return json.loads(raw)


class WatchedConfig:
    """Polls `path` and reloads on content change.

    Usage:
        cfg = WatchedConfig(path, default={})
        cfg.on_change(lambda data: manager.enqueue_all("Profile"))
        cfg.start()
        ... cfg.data ...
        cfg.stop()
    """

    def __init__(self, path: str, *, default: Any = None,
                 poll_interval: float = 0.2):
        self.path = path
        self.poll_interval = poll_interval
        self.data: Any = default
        self._callbacks: list[Callable[[Any], None]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_sig: tuple | None = None
        self._load(initial=True)

    def on_change(self, cb: Callable[[Any], None]) -> None:
        self._callbacks.append(cb)

    def _signature(self) -> tuple | None:
        try:
            real = os.path.realpath(self.path)  # symlink-swap aware
            st = os.stat(real)
            return (real, st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def _load(self, initial: bool = False) -> None:
        sig = self._signature()
        if sig == self._last_sig:
            return
        self._last_sig = sig
        if sig is None:
            if not initial:
                log.warning("watched config %s disappeared; keeping last "
                            "value", self.path)
            return
        try:
            with open(sig[0]) as f:
                data = _parse(self.path, f.read())
        except Exception as e:  # noqa: BLE001 — keep serving old config
            log.warning("watched config %s unreadable (%s); keeping last "
                        "value", self.path, e)
            return
        self.data = data
        if not initial:
            for cb in self._callbacks:
                try:
                    cb(data)
                except Exception:  # noqa: BLE001
                    log.exception("config change callback failed")

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            self._load()

    def start(self) -> "WatchedConfig":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def __enter__(self) -> "WatchedConfig":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
