"""Profiling: XLA profiler traces, first-compile latency, step timing.

The reference has no tracing/profiling at all (SURVEY.md §5 "Tracing /
profiling — absent"); its observability is metrics + logs. The TPU
replacement is the XLA profiler (TensorBoard profile plugin reads the
trace directory) plus the platform's north-star latency metric
(BASELINE.md): **pod-to-first-XLA-compile seconds** — how long a user
waits between pod start and a first compiled step.

Pod start time comes from `KFTPU_POD_START_TIME` (epoch seconds,
injected by the TPU webhook alongside the topology env); fallback is
process start.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Callable

import jax

_PROCESS_START = time.time()
POD_START_ENV = "KFTPU_POD_START_TIME"


def pod_start_time() -> float:
    raw = os.environ.get(POD_START_ENV, "")
    try:
        return float(raw)
    except ValueError:
        return _PROCESS_START


def time_to_first_compile(
    fn: Callable[..., Any], *args: Any, **kwargs: Any
) -> tuple[float, Any]:
    """Run `jit(fn)(*args)` once and return (seconds since pod start at
    completion of the first compile+execute, result). The BASELINE
    "pod-to-first-XLA-compile" measurement."""
    out = jax.jit(fn)(*args, **kwargs)
    jax.block_until_ready(out)
    return time.time() - pod_start_time(), out


@contextlib.contextmanager
def trace(logdir: str, tracer: Any = None):
    """XLA profiler trace → `logdir` (open with TensorBoard's profile
    plugin). Wraps steps of interest:

        with profiling.trace("/tmp/profile"):
            state, loss = trainer.step(state, batch, targets)

    Pass an `obs.Tracer` to also drop an `xla.profile` span into the
    app-level trace ring, marking WHICH wall-clock window the heavy XLA
    trace covers — /debug/traces shows the window, TensorBoard's
    profile plugin shows what happened inside it.
    """
    ctx = (tracer.span("xla.profile", logdir=logdir)
           if tracer is not None else contextlib.nullcontext())
    with ctx:
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()


class StepTimer:
    """Blocking step timer with percentile summary — a thin adapter
    over `obs.profiling.PhaseProfiler` (ISSUE 8): every recorded step
    is a `name` phase on the profiler, so training processes get the
    same step-anatomy aggregation (totals, rolling percentiles,
    counter tracks) the serving batcher has, and `summary()` uses the
    same quantile interpolation as `obs.metrics.Histogram.quantile`
    (`sample_quantile` — the old naive index pick disagreed with the
    histogram-side p95 asserted by the tenants loadtest).

    `with timer.step(): ...` — the exit blocks on `ready` (pass the
    step's output) so async dispatch doesn't fake a fast step.

    Optional obs bridge: give it a `tracer` and/or `histogram` and each
    timed step also becomes a span (named `name`) and a histogram
    observation — the summary here stays process-local, the histogram
    is what /metrics scrapes. Pass a shared `profiler` (the Trainer
    passes its own) to aggregate into an existing step anatomy.
    """

    def __init__(self, tracer: Any = None, histogram: Any = None,
                 name: str = "train.step", profiler: Any = None):
        from kubeflow_tpu.obs.profiling import PhaseProfiler

        self.durations: list[float] = []
        self.tracer = tracer
        self.histogram = histogram
        self.name = name
        self.profiler = (profiler if profiler is not None
                         else PhaseProfiler(phases=(name,)))

    @contextlib.contextmanager
    def step(self, ready: Any = None, **attrs: Any):
        ctx = (self.tracer.span(self.name, **attrs)
               if self.tracer is not None else contextlib.nullcontext())
        with ctx:
            t0 = time.perf_counter()
            yield
            if ready is not None:
                jax.block_until_ready(ready)
            self.record(time.perf_counter() - t0)

    def record(self, seconds: float) -> None:
        self.durations.append(seconds)
        self.profiler.record(self.name, seconds)
        if self.histogram is not None:
            self.histogram.observe(seconds)

    def summary(self) -> dict[str, float]:
        from kubeflow_tpu.obs.metrics import sample_quantile

        if not self.durations:
            return {}
        xs = sorted(self.durations)
        return {
            "count": len(xs),
            "mean_s": sum(xs) / len(xs),
            "p50_s": sample_quantile(xs, 0.50),
            "p90_s": sample_quantile(xs, 0.90),
            "p99_s": sample_quantile(xs, 0.99),
            "max_s": xs[-1],
        }
