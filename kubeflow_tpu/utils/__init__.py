"""Cross-cutting utilities: profiling hooks, hot-reloaded config."""

from kubeflow_tpu.utils.config import WatchedConfig
from kubeflow_tpu.utils.profiling import (
    StepTimer,
    time_to_first_compile,
    trace,
)
