"""Desired-replica recommendation from fleet signals.

Pure math, pure Python: the control plane imports this (it must stay
jax-free), the router exposes it at `/fleet/autoscale`, and tests pin
it directly. Two signals, per the serving engine's actual bottlenecks:

- queue depth: admitted work beyond the slot capacity waits in the
  batcher's pending deque — sustained queue means the fleet is short
  on decode slots, the one resource continuous batching multiplexes;
- KV-pool pressure: a replica whose block pool is nearly exhausted
  defers admissions even with free slots (paged-KV accounting), so
  pool pressure scales the fleet BEFORE queue depth shows it.

The recommendation is a pure function of a replica-stats snapshot —
no internal state, no timers. Hysteresis lives in the math (scale down
only when the shrunken fleet still has `scale_down_headroom` spare),
smoothing across evaluations is the caller's job if it wants any.

The ModelServer controller consumes the recommendation through the
`kubeflow-tpu.dev/desired-replicas` annotation (see
controlplane/controllers/modelserver.py): whatever agent runs this
function — the router process, a cron, an operator — writes the
number there, and the controller clamps it to the spec's
[replicas, max_replicas] band and drains before removing.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Iterable

from kubeflow_tpu.fleet.registry import DEGRADED, DRAINING, READY


@dataclass(frozen=True)
class Recommendation:
    desired: int
    reason: str
    signals: dict


@dataclass(frozen=True)
class PoolRecommendation:
    """Per-pool desired counts for a disaggregated fleet."""

    prefill: int
    decode: int
    reason: str
    signals: dict

    @property
    def desired(self) -> int:
        return self.prefill + self.decode


def _get(rep: Any, name: str, default=0):
    """Stats accessor over either `registry.Replica` objects or plain
    dicts (the router's JSON snapshot round-trips through clients)."""
    if isinstance(rep, dict):
        return rep.get(name, default)
    return getattr(rep, name, default)


def recommend_replicas(replicas: Iterable[Any], *,
                       min_replicas: int = 1, max_replicas: int = 8,
                       kv_pressure_high: float = 0.9,
                       scale_down_headroom: float = 0.7) -> Recommendation:
    """Aggregate fleet stats into a desired replica count.

    - demand = active slots + queued requests across live (ready or
      degraded; draining/dead replicas are already on their way out)
      replicas, in slot units;
    - desired_by_load = ceil(demand / mean slots per replica): the
      smallest fleet whose slot capacity covers current demand;
    - KV pressure (max over live replicas of pool blocks used/total)
      above `kv_pressure_high` forces at least one extra replica even
      when slots are free — admission is about to start deferring;
    - scale-down needs headroom: shrink only if demand fits within
      `scale_down_headroom` of the SHRUNKEN fleet's capacity, so a
      fleet bouncing around a boundary does not flap.
    """
    if min_replicas < 1 or max_replicas < min_replicas:
        raise ValueError(
            f"need 1 <= min_replicas <= max_replicas, got "
            f"[{min_replicas}, {max_replicas}]")

    def clamp(n: int) -> int:
        return max(min_replicas, min(n, max_replicas))

    reps = list(replicas)
    live = [r for r in reps
            if _get(r, "state", READY) in (READY, DEGRADED)]
    # draining replicas are exiting capacity — surfaced as a signal so
    # the autoscale consumer can tell "shrinking on purpose" from
    # "shrunk by failures" when it reads the recommendation
    draining = sum(1 for r in reps
                   if _get(r, "state", READY) == DRAINING)
    n = len(live)
    if n == 0:
        return Recommendation(
            clamp(min_replicas), "no live replicas",
            {"live": 0, "demand": 0, "kv_pressure": 0.0,
             "draining": draining})

    queued = sum(_get(r, "queue_depth") for r in live)
    active = sum(_get(r, "active_slots") for r in live)
    slots = sum(_get(r, "max_slots") for r in live)
    slots_per = slots / n if slots else 1.0
    demand = active + queued

    kv_pressure = 0.0
    for r in live:
        total = _get(r, "kv_blocks_total")
        if total > 0:
            used = total - _get(r, "kv_blocks_free")
            kv_pressure = max(kv_pressure, used / total)

    desired = max(1, math.ceil(demand / slots_per))
    reason = (f"demand {demand} over {slots_per:g} slots/replica "
              f"needs {desired}")
    if kv_pressure >= kv_pressure_high:
        if n + 1 > desired:
            desired = n + 1
            reason = (f"kv pressure {kv_pressure:.2f} >= "
                      f"{kv_pressure_high:g}: scale out")
    if desired < n and demand > scale_down_headroom * desired * slots_per:
        desired = n
        reason = (f"hold at {n}: demand {demand} lacks "
                  f"{scale_down_headroom:g} headroom on fewer replicas")
    return Recommendation(clamp(desired), reason, {
        "live": n, "demand": demand, "queued": queued, "active": active,
        "slots_per_replica": round(slots_per, 2),
        "kv_pressure": round(kv_pressure, 4),
        "draining": draining,
    })


def split_pools(total: int, phase_seconds: dict) -> tuple[int, int]:
    """Split `total` replicas into (prefill, decode) proportional to
    the fleet's cumulative phase-time shares.

    `phase_seconds` is the summed `serving_step_phase_seconds` totals
    ({"prefill": s, "decode": s}) — the pool whose phase share
    dominates is the bottleneck and gets the larger slice; no other
    signal is needed (ISSUE 12). Each pool keeps at least one replica
    (a disaggregated fleet with an empty pool cannot serve at all),
    which requires `total >= 2`. With no phase signal yet (cold fleet)
    the split is even, decode taking the odd replica — decode is the
    steady-state phase a fresh fleet grows into."""
    if total < 2:
        raise ValueError(
            f"a disaggregated fleet needs >= 2 replicas, got {total}")
    p = float(phase_seconds.get("prefill", 0.0) or 0.0)
    d = float(phase_seconds.get("decode", 0.0) or 0.0)
    if p < 0.0 or d < 0.0:
        raise ValueError(
            f"phase seconds must be >= 0, got prefill={p} decode={d}")
    share = p / (p + d) if (p + d) > 0.0 else 0.5
    # round the DECODE side half-up (not banker's) so ties — the cold
    # even split included — hand decode the odd replica
    decode = int(math.floor(total * (1.0 - share) + 0.5))
    decode = max(1, min(decode, total - 1))
    return total - decode, decode


def recommend_pools(replicas: Iterable[Any], *,
                    min_replicas: int = 2, max_replicas: int = 8,
                    kv_pressure_high: float = 0.9,
                    scale_down_headroom: float = 0.7
                    ) -> PoolRecommendation:
    """Desired per-pool counts for a disaggregated fleet.

    The TOTAL comes from `recommend_replicas` (same demand + KV
    pressure + hysteresis math — disaggregation changes where capacity
    sits, not how much is needed); the SPLIT comes from the summed
    phase-seconds shares the replicas heartbeat
    (`Replica.phase_seconds`, fed by each replica's PhaseProfiler).
    `min_replicas` must be >= 2 so both pools can hold a replica."""
    if min_replicas < 2:
        raise ValueError(
            f"disaggregated fleets need min_replicas >= 2, "
            f"got {min_replicas}")
    reps = list(replicas)
    rec = recommend_replicas(
        reps, min_replicas=min_replicas, max_replicas=max_replicas,
        kv_pressure_high=kv_pressure_high,
        scale_down_headroom=scale_down_headroom)
    phases = {"prefill": 0.0, "decode": 0.0}
    for r in reps:
        if _get(r, "state", READY) not in (READY, DEGRADED):
            continue
        ph = _get(r, "phase_seconds", {}) or {}
        for k in phases:
            v = ph.get(k, 0.0) if isinstance(ph, dict) else 0.0
            if isinstance(v, (int, float)) and v >= 0.0:
                phases[k] += float(v)
    prefill, decode = split_pools(max(2, rec.desired), phases)
    share = (phases["prefill"] / (phases["prefill"] + phases["decode"])
             if (phases["prefill"] + phases["decode"]) > 0.0 else 0.5)
    reason = (f"{rec.reason}; prefill phase share {share:.2f} "
              f"-> {prefill}p/{decode}d")
    signals = dict(rec.signals)
    signals["phase_seconds"] = {k: round(v, 4) for k, v in phases.items()}
    signals["prefill_share"] = round(share, 4)
    return PoolRecommendation(prefill, decode, reason, signals)
