"""Desired-replica recommendation from fleet signals.

Pure math, pure Python: the control plane imports this (it must stay
jax-free), the router exposes it at `/fleet/autoscale`, and tests pin
it directly. Two signals, per the serving engine's actual bottlenecks:

- queue depth: admitted work beyond the slot capacity waits in the
  batcher's pending deque — sustained queue means the fleet is short
  on decode slots, the one resource continuous batching multiplexes;
- KV-pool pressure: a replica whose block pool is nearly exhausted
  defers admissions even with free slots (paged-KV accounting), so
  pool pressure scales the fleet BEFORE queue depth shows it.

The recommendation is a pure function of a replica-stats snapshot —
no internal state, no timers. Hysteresis lives in the math (scale down
only when the shrunken fleet still has `scale_down_headroom` spare),
smoothing across evaluations is the caller's job if it wants any.

The ModelServer controller consumes the recommendation through the
`kubeflow-tpu.dev/desired-replicas` annotation (see
controlplane/controllers/modelserver.py): whatever agent runs this
function — the router process, a cron, an operator — writes the
number there, and the controller clamps it to the spec's
[replicas, max_replicas] band and drains before removing.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Iterable

from kubeflow_tpu.fleet.registry import DEGRADED, DRAINING, READY


@dataclass(frozen=True)
class Recommendation:
    desired: int
    reason: str
    signals: dict


def _get(rep: Any, name: str, default=0):
    """Stats accessor over either `registry.Replica` objects or plain
    dicts (the router's JSON snapshot round-trips through clients)."""
    if isinstance(rep, dict):
        return rep.get(name, default)
    return getattr(rep, name, default)


def recommend_replicas(replicas: Iterable[Any], *,
                       min_replicas: int = 1, max_replicas: int = 8,
                       kv_pressure_high: float = 0.9,
                       scale_down_headroom: float = 0.7) -> Recommendation:
    """Aggregate fleet stats into a desired replica count.

    - demand = active slots + queued requests across live (ready or
      degraded; draining/dead replicas are already on their way out)
      replicas, in slot units;
    - desired_by_load = ceil(demand / mean slots per replica): the
      smallest fleet whose slot capacity covers current demand;
    - KV pressure (max over live replicas of pool blocks used/total)
      above `kv_pressure_high` forces at least one extra replica even
      when slots are free — admission is about to start deferring;
    - scale-down needs headroom: shrink only if demand fits within
      `scale_down_headroom` of the SHRUNKEN fleet's capacity, so a
      fleet bouncing around a boundary does not flap.
    """
    if min_replicas < 1 or max_replicas < min_replicas:
        raise ValueError(
            f"need 1 <= min_replicas <= max_replicas, got "
            f"[{min_replicas}, {max_replicas}]")

    def clamp(n: int) -> int:
        return max(min_replicas, min(n, max_replicas))

    reps = list(replicas)
    live = [r for r in reps
            if _get(r, "state", READY) in (READY, DEGRADED)]
    # draining replicas are exiting capacity — surfaced as a signal so
    # the autoscale consumer can tell "shrinking on purpose" from
    # "shrunk by failures" when it reads the recommendation
    draining = sum(1 for r in reps
                   if _get(r, "state", READY) == DRAINING)
    n = len(live)
    if n == 0:
        return Recommendation(
            clamp(min_replicas), "no live replicas",
            {"live": 0, "demand": 0, "kv_pressure": 0.0,
             "draining": draining})

    queued = sum(_get(r, "queue_depth") for r in live)
    active = sum(_get(r, "active_slots") for r in live)
    slots = sum(_get(r, "max_slots") for r in live)
    slots_per = slots / n if slots else 1.0
    demand = active + queued

    kv_pressure = 0.0
    for r in live:
        total = _get(r, "kv_blocks_total")
        if total > 0:
            used = total - _get(r, "kv_blocks_free")
            kv_pressure = max(kv_pressure, used / total)

    desired = max(1, math.ceil(demand / slots_per))
    reason = (f"demand {demand} over {slots_per:g} slots/replica "
              f"needs {desired}")
    if kv_pressure >= kv_pressure_high:
        if n + 1 > desired:
            desired = n + 1
            reason = (f"kv pressure {kv_pressure:.2f} >= "
                      f"{kv_pressure_high:g}: scale out")
    if desired < n and demand > scale_down_headroom * desired * slots_per:
        desired = n
        reason = (f"hold at {n}: demand {demand} lacks "
                  f"{scale_down_headroom:g} headroom on fewer replicas")
    return Recommendation(clamp(desired), reason, {
        "live": n, "demand": demand, "queued": queued, "active": active,
        "slots_per_replica": round(slots_per, 2),
        "kv_pressure": round(kv_pressure, 4),
        "draining": draining,
    })
