"""Live model rollout: trainer→fleet continuous deployment (ISSUE 18).

The repo owns both halves of the train/serve loop, but until now they
only met through a static `--checkpoint` path at boot. This module is
the missing plane between them: the elastic chief publishes each
COMMITTED checkpoint to a `VersionRegistry` (`POST /fleet/versions`),
and a `RolloutManager` running in the router process rolls it across
the fleet with the primitives the repo already has — drain/migrate
(no in-flight sequence ever sees a reload), `/v1/reload` (drain-then-
swap on the replica), version-labelled heartbeats, and the PR 6 SLO
engine as the canary judge.

State machine (one rollout per published version):

    published ──> canarying ──> baking ──> promoting ──> completed
                      │            │           │
                      └────────────┴───────────┴──────> rolled_back

  canarying  — one replica drained (in-flight KV migrated to peers),
               reloaded to the candidate, waiting for it to re-register
               with the new `version` label in its heartbeat
  baking     — the canary serves real + probe traffic while the
               manager's SloEngine watches version-labelled TTFT and
               error events over a configurable bake window
  promoting  — the bake held: remaining replicas reload one at a time,
               each drained (KV migrated) first, so the flood never
               sees a failure
  rolled_back— the bake (or any reload) burned: every touched replica
               is reloaded back to the prior version, best-effort
  completed  — every live replica heartbeats the new version

Every phase transition is booked as a first-class event in the
conservation-checked `RolloutLedger` (the `DecisionLedger` discipline:
no transition vanishes un-booked, none is double-counted; every
rollout that starts ends active or terminal), served at
`GET /fleet/rollouts` and fed into zero-seeded `fleet_rollout_*`
metrics plus `rollout.phase` spans.

Import discipline: pure Python — no aiohttp, no jax. The router
injects the I/O (`drain_fn`/`reload_fn`/`probe_fn` async callables),
which is also what makes the state machine drivable on a fake clock
in tier-1 tests.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import deque
from typing import Any, Callable

from kubeflow_tpu import obs as obs_lib
from kubeflow_tpu.fleet.registry import DEGRADED, READY, ReplicaRegistry

log = logging.getLogger(__name__)

# Closed set of rollout phases. These become the `phase` label on
# `fleet_rollout_transitions_total`, so the set is CLOSED by design.
PHASES = ("published", "canarying", "baking", "promoting",
          "rolled_back", "completed")
# A rollout whose newest phase is terminal is finished; anything else
# is the (single) active rollout.
TERMINAL_PHASES = ("rolled_back", "completed")

# Closed outcome set for `fleet_rollout_reloads_total`.
RELOAD_OUTCOMES = ("ok", "failed")

# Version-entry lifecycle in the VersionRegistry (NOT a metric label —
# the phase label above is the observable vocabulary).
V_PENDING = "pending"        # published, not yet rolled
V_ROLLING = "rolling"        # the active rollout's candidate
V_LIVE = "live"              # promoted fleet-wide (current)
V_ROLLED_BACK = "rolled_back"
V_SUPERSEDED = "superseded"  # displaced by a newer publish/promote

_MAX_RECORDS = 256
_MAX_VERSIONS = 64

_VERSION_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyz"
    "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def valid_version(v: Any) -> bool:
    """Version names become metric labels and heartbeat fields, so
    they are validated at every door with ONE predicate: 1..64 chars
    from [A-Za-z0-9._-]. (`serving.server` and `fleet.registry` both
    import this — the vocabulary may not drift.)"""
    return (isinstance(v, str) and 0 < len(v) <= 64
            and all(c in _VERSION_CHARS for c in v))


class VersionRegistry:
    """Ordered store of published model versions (the rollout queue).

    The elastic chief POSTs each COMMITTED checkpoint here; entries
    carry the opaque `source` spec a replica's `/v1/reload` consumes
    (`{"checkpoint": dir, "step": n}` or `{"seed": n}`, plus the chaos
    harness's optional `defect`). `current` is the fleet-wide live
    version ("" until a rollout completes). Event-loop owned, like
    `ReplicaRegistry` — no lock.
    """

    def __init__(self, *, max_versions: int = _MAX_VERSIONS,
                 wall: Callable[[], float] = time.time):
        self._wall = wall
        self.max_versions = max_versions
        self._entries: dict[str, dict] = {}  # insertion-ordered
        self.current = ""
        # Bound by the consuming layer (FleetObs.bind_rollout) to the
        # fleet_rollout_published_total counter.
        self.on_publish: Callable[[dict], None] | None = None

    def publish(self, version: str, *, model: str = "",
                source: dict | None = None,
                step: int | None = None) -> tuple[dict, bool]:
        """Register one version. Idempotent by name: re-publishing an
        existing version returns `(entry, False)` untouched — the
        chief re-announcing a checkpoint after a coordinator blip must
        not restart a finished rollout. Returns `(entry, created)`."""
        if not valid_version(version):
            raise ValueError(
                f"invalid version {version!r} (1..64 chars from "
                "[A-Za-z0-9._-])")
        existing = self._entries.get(version)
        if existing is not None:
            return existing, False
        entry = {
            "version": version,
            "model": str(model or ""),
            "source": dict(source or {}),
            "step": int(step) if isinstance(step, int) else None,
            "published_at": self._wall(),
            "status": V_PENDING,
        }
        self._entries[version] = entry
        # bounded: drop the OLDEST non-current entry past the cap
        while len(self._entries) > self.max_versions:
            for old in self._entries:
                if old != self.current:
                    del self._entries[old]
                    break
            else:  # pragma: no cover — cap >= 1 keeps current
                break
        if self.on_publish is not None:
            try:
                self.on_publish(entry)
            except Exception:  # noqa: BLE001 — hooks never crash the door
                pass
        return entry, True

    def get(self, version: str) -> dict | None:
        return self._entries.get(version)

    def entries(self) -> list[dict]:
        return [dict(e) for e in self._entries.values()]

    def latest_pending(self) -> dict | None:
        """Newest pending entry — the rollout candidate. Older pending
        entries are superseded by it (the trainer publishes every
        committed save; only the newest is worth a bake window)."""
        pending = [e for e in self._entries.values()
                   if e["status"] == V_PENDING]
        if not pending:
            return None
        for stale in pending[:-1]:
            stale["status"] = V_SUPERSEDED
        return pending[-1]

    def set_current(self, version: str) -> None:
        """Promote `version` to fleet-wide live; the previous current
        entry (if tracked) becomes superseded."""
        prev = self._entries.get(self.current)
        if prev is not None and prev["status"] == V_LIVE:
            prev["status"] = V_SUPERSEDED
        self.current = version
        entry = self._entries.get(version)
        if entry is not None:
            entry["status"] = V_LIVE

    def snapshot(self) -> dict:
        return {"current": self.current, "versions": self.entries()}


class RolloutLedger:
    """Conservation-checked phase accounting for rollouts.

    The `DecisionLedger` discipline applied to deployment: every phase
    transition is booked exactly once into a closed phase set, so

        transitions == sum(phases over all phases)

    and every rollout that ever published is either still active or
    ended in exactly one terminal phase:

        started == finished + active

    Both equalities are asserted by tests and `ci/obs_check rollout`.
    Hook exceptions are swallowed — the ledger must never crash the
    rollout loop it audits.
    """

    def __init__(self, *, max_records: int = _MAX_RECORDS,
                 wall: Callable[[], float] = time.time):
        self._lock = threading.Lock()
        self._wall = wall
        self.transitions = 0
        self.phases = {p: 0 for p in PHASES}
        self.started = 0
        self.finished = 0
        # version -> ordered phase history (the audit spine)
        self._rollouts: dict[str, list[str]] = {}
        self._records: deque = deque(maxlen=max_records)
        # Bound by the consuming layer to fleet_rollout_transitions_total.
        self.on_phase: Callable[[str, str], None] | None = None

    def note(self, version: str, phase: str, *,
             evidence: dict | None = None) -> dict:
        """Book one phase transition for `version`. Idempotence is the
        CALLER's job (the manager's state machine transitions once);
        the ledger's job is that whatever was booked is conserved."""
        if phase not in PHASES:
            raise ValueError(f"unknown rollout phase {phase!r}")
        rec = {
            "wall": self._wall(),
            "version": version,
            "phase": phase,
            "evidence": dict(evidence or {}),
        }
        with self._lock:
            history = self._rollouts.setdefault(version, [])
            if phase == "published" and not history:
                self.started += 1
            if (phase in TERMINAL_PHASES
                    and (not history
                         or history[-1] not in TERMINAL_PHASES)):
                self.finished += 1
            history.append(phase)
            self.transitions += 1
            self.phases[phase] += 1
            self._records.append(rec)
        self._hook(self.on_phase, version, phase)
        return rec

    def phase_of(self, version: str) -> str | None:
        with self._lock:
            history = self._rollouts.get(version)
            return history[-1] if history else None

    def verdict(self, version: str) -> str:
        """Terminal phase of `version`'s rollout, or "active"/"unknown"
        — what the loadtest asserts against `/fleet/rollouts`."""
        phase = self.phase_of(version)
        if phase is None:
            return "unknown"
        return phase if phase in TERMINAL_PHASES else "active"

    @property
    def active(self) -> int:
        with self._lock:
            return sum(
                1 for h in self._rollouts.values()
                if h and h[-1] not in TERMINAL_PHASES
                and "published" in h)

    @property
    def conserved(self) -> bool:
        with self._lock:
            by_history = sum(
                1 for h in self._rollouts.values()
                if h and h[-1] not in TERMINAL_PHASES
                and "published" in h)
            return (self.transitions == sum(self.phases.values())
                    and self.started == self.finished + by_history)

    def records(self, limit: int | None = None) -> list[dict]:
        with self._lock:
            recs = [dict(r) for r in self._records]
        return recs[-limit:] if limit else recs

    def snapshot(self) -> dict:
        """Jsonable summary for `GET /fleet/rollouts`."""
        with self._lock:
            active = sum(
                1 for h in self._rollouts.values()
                if h and h[-1] not in TERMINAL_PHASES
                and "published" in h)
            return {
                "transitions": self.transitions,
                "phases": dict(self.phases),
                "started": self.started,
                "finished": self.finished,
                "active": active,
                "rollouts": {v: {"history": list(h),
                                 "phase": h[-1] if h else None}
                             for v, h in self._rollouts.items()},
                "conserved": (
                    self.transitions == sum(self.phases.values())
                    and self.started == self.finished + active),
            }

    @staticmethod
    def _hook(fn, *args) -> None:
        if fn is None:
            return
        try:
            fn(*args)
        except Exception:  # noqa: BLE001 — swallowed by contract
            pass


def rollout_slos(*, ttft_threshold_s: float = 1.5,
                 ttft_objective: float = 0.95,
                 error_objective: float = 0.99) -> list:
    """The canary judge's objectives: version-labelled TTFT (threshold
    SLO over probe + routed latencies attributed to the candidate) and
    error rate. One definition site — the manager and the router's
    shared-registry wiring must agree."""
    return [
        obs_lib.Slo("rollout_canary_ttft", ttft_objective,
                    threshold_s=ttft_threshold_s,
                    description="canary answers under the TTFT "
                                "threshold during the bake window"),
        obs_lib.Slo("rollout_canary_errors", error_objective,
                    description="canary answers without a 5xx during "
                                "the bake window"),
    ]


class RolloutManager:
    """Canary → bake → promote state machine over the replica fleet.

    Runs in the router process beside the `Controller`; the router
    injects the three I/O callables so this module stays pure:

      drain_fn(replica_id) -> awaitable     (drain_and_migrate: mark
            draining + push in-flight KV to peers — the flood never
            sees a reload)
      reload_fn(replica, entry) -> awaitable bool   (POST /v1/reload
            with the entry's source spec; True = swap confirmed)
      probe_fn(replica) -> awaitable (seconds, ok) | None   (one
            direct canary generate — the active half of the judge;
            passive version-labelled routed traffic feeds in through
            `observe_request`)

    `step()` advances the machine by at most one phase action and is
    the unit tests and `ci/obs_check rollout` drive on a fake clock;
    `run()` is the router's background loop around it.
    """

    def __init__(self, registry: ReplicaRegistry,
                 versions: VersionRegistry, ledger: RolloutLedger, *,
                 drain_fn=None, reload_fn=None, probe_fn=None,
                 slo_engine=None,
                 bake_window_s: float = 30.0,
                 bake_min_probes: int = 4,
                 burn_threshold: float = 2.0,
                 ttft_threshold_s: float = 1.5,
                 confirm_timeout_s: float = 60.0,
                 interval_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 tracer=None,
                 on_reload: Callable[[str], None] | None = None):
        self.registry = registry
        self.versions = versions
        self.ledger = ledger
        self.drain_fn = drain_fn
        self.reload_fn = reload_fn
        self.probe_fn = probe_fn
        self.bake_window_s = float(bake_window_s)
        self.bake_min_probes = int(bake_min_probes)
        self.burn_threshold = float(burn_threshold)
        self.confirm_timeout_s = float(confirm_timeout_s)
        self.interval_s = float(interval_s)
        self.clock = clock
        self.tracer = tracer if tracer is not None else obs_lib.Tracer()
        self.on_reload = on_reload
        self.slo = slo_engine if slo_engine is not None else \
            obs_lib.SloEngine(
                rollout_slos(ttft_threshold_s=ttft_threshold_s),
                short_window_s=max(bake_window_s, 1.0),
                long_window_s=max(bake_window_s, 1.0) * 10,
                clock=clock)
        for slo in rollout_slos(ttft_threshold_s=ttft_threshold_s):
            self.slo.add(slo)  # shared engines merge; first def wins
        # manual knob: while pinned, no NEW rollout starts (an active
        # one finishes its course) — the operator's change freeze
        self.pinned = False
        self._rollback_requested = ""
        # the single active rollout, or None
        self.active: dict | None = None

    # -- feed side (router's _routed_generate) ---------------------------

    def observe_request(self, version: str, seconds: float,
                        ok: bool) -> None:
        """Passive judge feed: one routed generate answered by a
        replica heartbeating `version`. Only the active candidate's
        events count (the judge compares the candidate against its SLO
        objectives, not against other versions). Never throws."""
        try:
            act = self.active
            if act is None or version != act["version"]:
                return
            if act["phase"] not in ("canarying", "baking", "promoting"):
                return
            self.slo.observe("rollout_canary_ttft", seconds)
            self.slo.record("rollout_canary_errors", ok)
            act["observed"] += 1
        except Exception:  # noqa: BLE001 — feeders never crash routing
            pass

    # -- manual knobs (POST /fleet/rollouts) -----------------------------

    def request_rollback(self, reason: str = "manual") -> bool:
        """Abort the active rollout on the next step. Returns whether
        there was one to abort."""
        if self.active is None:
            return False
        self._rollback_requested = reason or "manual"
        return True

    def pin(self, pinned: bool = True) -> None:
        self.pinned = bool(pinned)

    # -- state machine ----------------------------------------------------

    async def step(self) -> None:
        """Advance by at most one phase action. Safe to call with no
        replicas, no pending versions, or mid-rollout."""
        if self.active is None:
            if self.pinned:
                return
            entry = self.versions.latest_pending()
            if entry is not None:
                await self._start(entry)
            return
        if self._rollback_requested:
            reason, self._rollback_requested = \
                self._rollback_requested, ""
            await self._rollback(reason)
            return
        phase = self.active["phase"]
        if phase == "canarying":
            await self._step_canarying()
        elif phase == "baking":
            await self._step_baking()
        elif phase == "promoting":
            await self._step_promoting()

    async def run(self) -> None:
        """Background loop for the router process (cancelled on app
        cleanup). Exceptions are logged, never fatal — a rollout plane
        that can crash the router would be worse than no rollouts."""
        while True:
            await asyncio.sleep(self.interval_s)
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                log.exception("rollout step failed")

    def _transition(self, phase: str, **evidence) -> None:
        act = self.active
        version = act["version"] if act else evidence.get("version", "")
        with self.tracer.span("rollout.phase", version=version,
                              phase=phase):
            self.ledger.note(version, phase, evidence=evidence)
        if act is not None:
            act["phase"] = phase
            act["t_phase"] = self.clock()
        log.info("rollout %s -> %s %s", version, phase, evidence or "")

    def _live_replicas(self) -> list:
        return [r for r in self.registry.replicas()
                if r.state in (READY, DEGRADED)]

    async def _reload_replica(self, rep, entry) -> bool:
        """Drain-then-reload one replica: migrate its in-flight KV to
        peers, then POST the version's source spec to /v1/reload. The
        outcome feeds fleet_rollout_reloads_total either way."""
        ok = False
        try:
            if self.drain_fn is not None:
                await self.drain_fn(rep.id)
            if self.reload_fn is not None:
                ok = bool(await self.reload_fn(rep, entry))
        except Exception as e:  # noqa: BLE001 — a dead replica is a failed reload
            log.warning("rollout: reload of %s to %s failed: %s",
                        rep.id, entry["version"], e)
            ok = False
        self._hook_reload("ok" if ok else "failed")
        if ok:
            self.active["touched"].append(rep.id)
        return ok

    def _hook_reload(self, outcome: str) -> None:
        if self.on_reload is None:
            return
        try:
            self.on_reload(outcome)
        except Exception:  # noqa: BLE001 — swallowed by contract
            pass

    async def _start(self, entry: dict) -> None:
        candidates = [r for r in self._live_replicas()
                      if r.version != entry["version"]]
        if not candidates:
            return  # nothing to roll onto yet; stay pending
        # least-loaded canary: draining it strands the fewest sequences
        canary = min(candidates, key=lambda r: (r.load(), r.id))
        prior = self.versions.current
        entry["status"] = V_ROLLING
        self.active = {
            "version": entry["version"],
            "prior": prior,
            "phase": "published",
            "canary": canary.id,
            "touched": [],
            "observed": 0,
            "probes": 0,
            "t_phase": self.clock(),
            "t_start": self.clock(),
        }
        # the "published" booking opens this rollout in the ledger
        # (started++) — conservation needs the open BEFORE any
        # terminal phase can close it
        self._transition("published", model=entry.get("model", ""),
                         step=entry.get("step"))
        self._transition("canarying", canary=canary.id, prior=prior)
        if not await self._reload_replica(canary, entry):
            await self._rollback("canary_reload_failed")

    def _confirmed(self, rid: str) -> bool:
        rep = self.registry.get(rid)
        return (rep is not None
                and rep.version == self.active["version"]
                and rep.state in (READY, DEGRADED))

    async def _step_canarying(self) -> None:
        act = self.active
        if self._confirmed(act["canary"]):
            self._transition("baking", canary=act["canary"])
            return
        if self.clock() - act["t_phase"] > self.confirm_timeout_s:
            await self._rollback("canary_confirm_timeout")

    def _burn(self) -> float:
        rates = self.slo.burn_rates()
        return max(rates.get(("rollout_canary_ttft", "short"), 0.0),
                   rates.get(("rollout_canary_errors", "short"), 0.0))

    async def _probe_canary(self) -> None:
        act = self.active
        rep = self.registry.get(act["canary"])
        if self.probe_fn is None or rep is None:
            return
        try:
            res = await self.probe_fn(rep)
        except Exception:  # noqa: BLE001 — a probe that died is a bad event
            res = (self.confirm_timeout_s, False)
        if res is None:
            return
        seconds, ok = res
        self.slo.observe("rollout_canary_ttft", float(seconds))
        self.slo.record("rollout_canary_errors", bool(ok))
        act["probes"] += 1

    async def _step_baking(self) -> None:
        act = self.active
        await self._probe_canary()
        samples = act["probes"] + act["observed"]
        burn = self._burn()
        if samples >= self.bake_min_probes \
                and burn >= self.burn_threshold:
            await self._rollback("slo_burn", burn=round(burn, 3),
                                 samples=samples)
            return
        if (self.clock() - act["t_phase"] >= self.bake_window_s
                and samples >= self.bake_min_probes):
            self._transition("promoting", burn=round(burn, 3),
                             samples=samples)

    async def _step_promoting(self) -> None:
        act = self.active
        entry = self.versions.get(act["version"])
        if entry is None:  # pragma: no cover — entries outlive rollouts
            await self._rollback("version_vanished")
            return
        burn = self._burn()
        if burn >= self.burn_threshold:
            await self._rollback("slo_burn_during_promote",
                                 burn=round(burn, 3))
            return
        remaining = [r for r in self._live_replicas()
                     if r.version != act["version"]]
        todo = [r for r in remaining if r.id not in act["touched"]]
        if todo:
            # one replica per step: the fleet loses at most one
            # replica's capacity at a time, exactly like the canary
            target = min(todo, key=lambda r: (r.load(), r.id))
            if not await self._reload_replica(target, entry):
                await self._rollback("reload_failed",
                                     replica=target.id)
            return
        if not remaining:
            self.versions.set_current(act["version"])
            self._transition("completed",
                             replicas=len(self._live_replicas()))
            self.active = None
            return
        # every remaining replica was reloaded but has not re-
        # registered with the new version yet: wait, bounded
        if self.clock() - act["t_phase"] > \
                self.confirm_timeout_s + self.bake_window_s:
            await self._rollback("promote_confirm_timeout")

    async def _rollback(self, reason: str, **evidence) -> None:
        act = self.active
        entry = self.versions.get(act["version"])
        if entry is not None:
            entry["status"] = V_ROLLED_BACK
        self._transition("rolled_back", reason=reason,
                         prior=act["prior"], touched=len(act["touched"]),
                         **evidence)
        prior_entry = self.versions.get(act["prior"])
        if prior_entry is not None and prior_entry.get("source"):
            # restore every touched replica to the prior version,
            # best-effort (a replica that will not come back is the
            # registry's problem, not the rollout's)
            for rid in list(act["touched"]):
                rep = self.registry.get(rid)
                if rep is None:
                    continue
                try:
                    if self.drain_fn is not None:
                        await self.drain_fn(rid)
                    if self.reload_fn is not None:
                        restored = bool(
                            await self.reload_fn(rep, prior_entry))
                        self._hook_reload(
                            "ok" if restored else "failed")
                except Exception:  # noqa: BLE001 — best-effort by contract
                    self._hook_reload("failed")
        else:
            log.warning(
                "rollout %s rolled back but prior version %r has no "
                "reloadable source — touched replicas keep the bad "
                "weights until the next publish", act["version"],
                act["prior"])
        self.active = None

    # -- read side ---------------------------------------------------------

    def describe(self) -> dict:
        """Jsonable live state for `GET /fleet/rollouts`."""
        act = None
        if self.active is not None:
            act = {k: self.active[k]
                   for k in ("version", "prior", "phase", "canary",
                             "touched", "probes", "observed")}
            act["phase_age_s"] = round(
                self.clock() - self.active["t_phase"], 3)
        burn = self.slo.burn_rates()
        return {
            "active": act,
            "pinned": self.pinned,
            "current": self.versions.current,
            "config": {
                "bake_window_s": self.bake_window_s,
                "bake_min_probes": self.bake_min_probes,
                "burn_threshold": self.burn_threshold,
                "confirm_timeout_s": self.confirm_timeout_s,
            },
            "burn": {f"{name}/{window}": round(v, 4)
                     for (name, window), v in sorted(burn.items())
                     if name.startswith("rollout_")},
        }
