"""Fleet router: the HTTP front door over N serving replicas.

Routes `POST /v1/models/{name}:generate` by consistent-hash prefix
affinity — the routing key is the request's first `kv_block_size`
tokens (the first block is what the replicas' radix prefix cache
indexes), so repeated prompts land on the replica that already holds
the cached KV and prefill only computes the suffix. When the affinity
target is unavailable (draining/dead) or overloaded, the request falls
back to the least-loaded replica; proxy failures retry on the next
candidate with exponential backoff; a request still unanswered after
`hedge_after_s` is duplicated to a second replica and the first
response wins (tail-latency insurance — the loser is cancelled).

The router is deliberately jax-free: it boots in milliseconds, knows
nothing about models beyond their names, and observes replicas purely
through the registration/heartbeat handshake
(`serving.server.enable_fleet_registration`) plus its own proxy
outcomes. Decisions are observable: `fleet_route_total{reason}`,
`fleet_hedge_wins_total`, `fleet_replicas{state}` (render-time
collector), a route-latency histogram, and spans whose
`replica_trace` attribute carries the replica's `X-Trace-Id` — one
trace id per hop, joined in the router's span attrs.

    from kubeflow_tpu.fleet.router import create_router_app
    web.run_app(create_router_app(block_size=64), port=9000)
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import secrets
import time

import aiohttp
from aiohttp import web

from kubeflow_tpu import obs as obs_lib
from kubeflow_tpu.fleet import autoscale
from kubeflow_tpu.fleet.registry import ReplicaRegistry
from kubeflow_tpu.obs import endpoints as obs_endpoints
from kubeflow_tpu.tenancy import TenancyConfig, TenantLedger, Throttled

log = logging.getLogger(__name__)

FLEET_KEY: web.AppKey = web.AppKey("fleet_state", object)

ROUTE_REASONS = ("affinity", "fallback", "hedge", "retry")

# Mirrors serving.server's byte tokenizer constants (BOS=1, bytes at
# +3): the router must hash "text" bodies to the SAME first block the
# replica will tokenize, without importing the jax-loaded server
# module. Drift is pinned by tests/test_fleet.py.
_BOS, _BYTE_OFFSET = 1, 3


def affinity_key(body: dict, block_size: int) -> bytes:
    """Routing key: the first `block_size`-aligned token block of the
    prompt. Requests sharing it co-locate on one replica (where the
    radix cache can serve it); malformed bodies key to b"" (no
    affinity — the replica will 400 them, but through a live one)."""
    toks = None
    if isinstance(body, dict):
        t = body.get("tokens")
        if (isinstance(t, list) and t and isinstance(t[0], list)
                and all(isinstance(x, int) and not isinstance(x, bool)
                        for x in t[0])):
            toks = t[0]
        elif isinstance(body.get("text"), str):
            toks = [_BOS] + [b + _BYTE_OFFSET
                             for b in body["text"].encode("utf-8")]
    if not toks:
        return b""
    return " ".join(str(x) for x in toks[:block_size]).encode()


def _byte_decode_fleet(ids) -> str:
    """Best-effort byte-tokenizer decode for SPLICED text-mode
    responses (mirrors the serving byte tokenizer: bytes at +3,
    specials below). Only used when the router itself rebuilds the
    text of a failed-over generation; replicas with a real tokenizer
    should use token-mode bodies through the fleet door."""
    return bytes(t - _BYTE_OFFSET for t in ids
                 if t >= _BYTE_OFFSET).decode("utf-8", errors="replace")


def _resume_from_checkpoint(body: dict, ck: dict,
                            sent: list) -> tuple[bytes | None, int]:
    """Failover re-dispatch body from a heartbeat checkpoint: replay
    prompt = checkpoint prompt (embeds any registered-prefix
    expansion, so 'prefix' is dropped) + every token the client
    already holds; budget = what remains. Returns (raw, remaining) —
    remaining <= 0 means the generation already completed."""
    toks = [int(t) for t in ck.get("tokens", [])]
    n_out = len(ck.get("out", []))
    prompt = toks[: len(toks) - n_out]
    remaining = int(ck.get("max_new", 0)) - len(sent)
    if remaining <= 0 or not prompt:
        return None, remaining
    nb = {k: v for k, v in body.items()
          if k not in ("text", "tokens", "prefix", "max_new")}
    nb["tokens"] = [prompt + [int(t) for t in sent]]
    nb["max_new"] = remaining
    return json.dumps(nb).encode(), remaining


def _resume_from_body(body: dict, sent: list) -> bytes | None:
    """Checkpoint-less failover for token-mode bodies with an explicit
    max_new: splice the delivered tokens onto the client's own prompt.
    (The 'prefix' field stays — the replica re-expands it exactly as
    the dead one did.) Returns None when the body is not resumable
    this way — the caller re-sends the original and skips."""
    t = body.get("tokens")
    if (not isinstance(t, list) or len(t) != 1
            or not isinstance(t[0], list)
            or not isinstance(body.get("max_new"), int)):
        return None
    remaining = body["max_new"] - len(sent)
    if remaining <= 0:
        return None
    nb = {k: v for k, v in body.items() if k not in ("tokens", "max_new")}
    nb["tokens"] = [list(t[0]) + [int(x) for x in sent]]
    nb["max_new"] = remaining
    return json.dumps(nb).encode()


def _splice_oneshot(payload: bytes, prepend: list,
                    text_mode: bool) -> bytes:
    """Merge a resumed one-shot response with the tokens the dead
    replica already produced: the client must see ONE complete row, as
    if nothing failed. Unparseable payloads pass through untouched."""
    try:
        pj = json.loads(payload)
        rows = pj["tokens"]
        rows[0] = [int(t) for t in prepend] + rows[0]
    except (KeyError, IndexError, TypeError, ValueError):
        return payload
    if text_mode:
        pj["text"] = _byte_decode_fleet(rows[0])
    return json.dumps(pj).encode()


def _parse_sse_event(raw: bytes) -> dict | None:
    """One `data: {...}` SSE frame -> dict, or None for anything the
    serving replicas don't emit (comments, malformed JSON)."""
    line = raw.strip()
    if not line.startswith(b"data:"):
        return None
    try:
        ev = json.loads(line[5:].strip())
    except (ValueError, UnicodeDecodeError):
        return None
    return ev if isinstance(ev, dict) else None


class FleetObs:
    """Router observability bundle (the serving `ServingObs` pattern):
    metric registry + tracer + the fleet_* instruments."""

    def __init__(self, reg: ReplicaRegistry, registry=None, tracer=None):
        from kubeflow_tpu.controlplane.metrics import (
            Counter,
            Gauge,
            Registry,
        )

        self.registry = registry if registry is not None else Registry()
        self.tracer = tracer if tracer is not None else obs_lib.Tracer()
        self.route_total = Counter(
            "fleet_route_total",
            "Routing decisions by reason: affinity (rendezvous target), "
            "fallback (least-loaded), retry (previous replica failed), "
            "hedge (duplicate dispatch after the latency deadline)",
            self.registry)
        self.hedge_wins = Counter(
            "fleet_hedge_wins_total",
            "Hedged duplicates that answered before the primary",
            self.registry)
        self.failover = Counter(
            "fleet_failover_total",
            "In-flight generations re-dispatched to a healthy replica "
            "after their replica failed mid-request (checkpoint resume "
            "or seamless stream splice)", self.registry)
        self.route_latency = obs_lib.get_or_create_histogram(
            self.registry, "fleet_route_duration_seconds",
            "Routed request latency through the router, by model and "
            "final routing reason")
        replicas_g = Gauge(
            "fleet_replicas",
            "Registered replicas by health state "
            "(ready/degraded/draining/dead)", self.registry)
        # Per-tenant routing accounting (X-Tenant header). With a
        # tenancy config, names resolve through it (bounded by
        # configuration); without one, raw header values pass the
        # cardinality guard so scanners can't mint unbounded series.
        self.tenant_requests = Counter(
            "fleet_tenant_requests_total",
            "Routed generate requests by tenant (X-Tenant header)",
            self.registry)
        self.tenant_throttled = Counter(
            "fleet_tenant_throttled_total",
            "Requests 429'd at the router door by the tenant's "
            "request bucket, before any replica dispatch",
            self.registry)
        self.tenant_guard = obs_lib.LabelGuard()
        # Federation: bounds the `replica` label on /fleet/metrics so a
        # churning fleet can't grow the merged exposition unboundedly.
        self.replica_guard = obs_lib.LabelGuard()
        # Router-side SLOs: end-to-end routed latency (what the CLIENT
        # experiences through the door, retries and hedges included)
        # and availability (5xx / no-replica-at-all are budget spends).
        self.slo = obs_lib.SloEngine([
            obs_lib.Slo("fleet_route_latency", 0.95, threshold_s=2.5,
                        description="95% of routed generates under "
                        "2.5 s end to end"),
            obs_lib.Slo("fleet_availability", 0.99,
                        description="99% of routed generates answered "
                        "by some replica without a 5xx"),
        ])
        try:
            self.registry.register(self.slo)
        except ValueError:
            pass  # shared registry already carries a burn-rate gauge
        circuit_g = Gauge(
            "fleet_circuit_open",
            "1 while the replica's circuit breaker is open (skipped by "
            "fresh routing picks until the half-open probe)",
            self.registry)
        # zero-seed so the series exist (at 0) before any traffic
        for reason in ROUTE_REASONS:
            self.route_total.inc(0, reason=reason)
        self.hedge_wins.inc(0)
        self.failover.inc(0)

        def collect():
            reg.sweep()
            for state, nn in reg.counts().items():
                replicas_g.set(nn, state=state)
            for rep in reg.replicas():
                circuit_g.set(int(reg.circuit_open(rep.id)),
                              replica=self.replica_guard.admit(rep.id))

        self.registry.register_collector(collect)


class _FleetState:
    # bounds on the heartbeat-fed checkpoint store: entries older than
    # the TTL describe requests that finished or already failed over
    CHECKPOINT_TTL_S = 60.0
    CHECKPOINT_CAP = 4096

    def __init__(self, registry: ReplicaRegistry, obs: FleetObs, *,
                 block_size: int, policy: str, hedge_after_s: float,
                 retries: int, backoff_s: float, timeout_s: float,
                 tenancy: TenancyConfig | None = None,
                 max_attempts: int | None = None, chaos=None):
        self.registry = registry
        self.obs = obs
        self.block_size = block_size
        self.policy = policy
        self.hedge_after_s = hedge_after_s
        self.retries = retries
        self.backoff_s = backoff_s
        self.timeout_s = timeout_s
        # retry BUDGET: total upstream dispatches one client request
        # may cost (primaries + retries + hedges together) — a slow
        # fleet must not amplify every request into an unbounded fan
        self.max_attempts = (max_attempts if max_attempts is not None
                             else retries + 2)
        self.session: aiohttp.ClientSession | None = None
        self.rr = 0  # round-robin cursor (policy="roundrobin" A/B arm)
        # fleet.chaos.ChaosInjector (loadtest --mode chaos): seeded
        # fault hooks on the router->replica path. None in production.
        self.chaos = chaos
        # request_id -> {"ck": checkpoint, "replica": id, "t": stamp}
        # fed by heartbeats; read by the failover paths when the
        # owning replica dies mid-request
        self.checkpoints: dict[str, dict] = {}
        # Router-side tenant rate limiting: the same TenancyConfig the
        # replicas run, enforced at the fleet door so a flooding tenant
        # is shed ONCE here instead of N times downstream. The replicas
        # keep their own ledgers (per-replica limits still apply).
        self.tenancy = tenancy
        self.ledger = TenantLedger(tenancy) if tenancy is not None \
            else None

    def ingest_checkpoints(self, replica_id: str, cks) -> None:
        """Fold one heartbeat's sequence checkpoints into the store
        (bounded: stale entries pruned, oldest dropped over the cap)."""
        now = time.monotonic()
        if isinstance(cks, list):
            for ck in cks[:512]:
                if not isinstance(ck, dict):
                    continue
                rid = str(ck.get("request_id", ""))
                if rid:
                    self.checkpoints[rid] = {
                        "ck": ck, "replica": replica_id, "t": now}
        stale = now - self.CHECKPOINT_TTL_S
        for rid in [r for r, e in self.checkpoints.items()
                    if e["t"] < stale]:
            del self.checkpoints[rid]
        while len(self.checkpoints) > self.CHECKPOINT_CAP:
            oldest = min(self.checkpoints, key=lambda r:
                         self.checkpoints[r]["t"])
            del self.checkpoints[oldest]

    def checkpoint_for(self, request_id: str) -> dict | None:
        entry = self.checkpoints.get(request_id)
        if entry is None or (time.monotonic() - entry["t"]
                             > self.CHECKPOINT_TTL_S):
            return None
        return entry["ck"]


class _UpstreamError(RuntimeError):
    """Replica-side failure (connect error, timeout, 5xx) — retryable
    on another replica, unlike a 4xx which is the client's problem."""


@web.middleware
async def _router_obs_middleware(request: web.Request, handler):
    st: _FleetState = request.app[FLEET_KEY]
    resource = getattr(request.match_info.route, "resource", None)
    route = getattr(resource, "canonical", None) or "unmatched"
    with st.obs.tracer.span("fleet.request", method=request.method,
                            route=route) as span:
        try:
            resp = await handler(request)
            span.attrs["status"] = resp.status
            if not resp.prepared:
                resp.headers.setdefault("X-Trace-Id", span.trace_id)
            return resp
        except web.HTTPException as exc:
            span.attrs["status"] = exc.status
            exc.headers.setdefault("X-Trace-Id", span.trace_id)
            raise


def _choose(st: _FleetState, key: bytes, exclude: set):
    """One routing decision under the configured policy. The
    "roundrobin" policy exists for the affinity-vs-random A/B
    (loadtest --fleet-policy roundrobin) and labels as fallback."""
    if st.policy == "roundrobin":
        pool = st.registry.routable(exclude)
        if not pool:
            st.registry.sweep()
            pool = st.registry.routable(exclude)
        if not pool:
            return None, "fallback"
        pool.sort(key=lambda r: r.id)
        st.rr += 1
        return pool[st.rr % len(pool)], "fallback"
    return st.registry.pick(key, exclude)


def _inject_trace_context(st: _FleetState, headers: dict) -> dict:
    """Propagate the CURRENT span's context into an upstream dispatch:
    the replica's middleware adopts `X-Trace-Id`/`X-Parent-Span` via
    `Tracer.span_from_remote`, so its segment commits under the
    router's trace id. Copied per dispatch — retries and hedges each
    carry the live span ids."""
    span = st.obs.tracer.current_span()
    if span is None:
        return headers
    return {**headers, "X-Trace-Id": span.trace_id,
            "X-Parent-Span": span.span_id}


async def _chaos_shadow(st: _FleetState, url: str, raw: bytes,
                        headers: dict) -> None:
    """Fire-and-forget duplicate dispatch (chaos 'duplicate' fault):
    exercises at-least-once delivery — the replica must tolerate the
    same request body arriving twice. The shadow's outcome is
    discarded."""
    try:
        async with st.session.post(
                url, data=raw, headers=headers,
                timeout=aiohttp.ClientTimeout(total=st.timeout_s)) as r:
            await r.read()
    except Exception:  # noqa: BLE001 — shadow outcomes never surface
        pass


async def _chaos_gate(st: _FleetState, rep, name: str, raw: bytes,
                      headers: dict) -> None:
    """Apply the injector's dispatch faults for one router->replica
    call: may sleep (delay), spawn a duplicate shadow dispatch, or
    raise `_UpstreamError` (drop)."""
    if st.chaos is None:
        return
    action = await st.chaos.before_dispatch(rep.id)
    if action == "duplicate":
        asyncio.ensure_future(_chaos_shadow(
            st, f"{rep.url}/v1/models/{name}:generate", raw, headers))
    elif action == "drop":
        raise _UpstreamError(f"chaos: dropped dispatch to {rep.id}")


async def _call_replica(st: _FleetState, rep, name: str, raw: bytes,
                        tried: set, headers: dict):
    """One proxied generate against one replica. Success returns
    (status, payload, replica, upstream_trace_id); replica-side
    failures mark the replica, add it to `tried`, and raise
    `_UpstreamError` so the caller moves on."""
    st.registry.note_dispatch(rep.id)
    try:
        await _chaos_gate(st, rep, name, raw, headers)
        async with st.session.post(
                f"{rep.url}/v1/models/{name}:generate", data=raw,
                headers=_inject_trace_context(st, headers),
                timeout=aiohttp.ClientTimeout(total=st.timeout_s)) as r:
            payload = await r.read()
            if r.status >= 500:
                raise _UpstreamError(
                    f"replica {rep.id} answered {r.status}")
            st.registry.note_success(rep.id)
            return r.status, payload, rep, r.headers.get("X-Trace-Id", "")
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
            _UpstreamError) as e:
        st.registry.note_failure(rep.id)
        tried.add(rep.id)
        raise _UpstreamError(str(e)) from e
    finally:
        st.registry.note_done(rep.id)


async def _race_hedged(st: _FleetState, primary, name: str, raw: bytes,
                       key: bytes, tried: set, model: str,
                       headers: dict, budget: list):
    """Dispatch to `primary`; past the hedge deadline, duplicate to a
    second replica and take whichever answers first. Every dispatch
    (primary and hedge alike) spends one unit of the request's attempt
    `budget` — a hedge is skipped once the budget is gone. Returns
    (status, payload, replica, hedge_won, upstream_trace) or None when
    every dispatched replica failed (all are in `tried` by then)."""
    budget[0] -= 1
    tasks = {asyncio.create_task(_call_replica(st, primary, name, raw,
                                               tried, headers))}
    hedged_id = None
    if st.hedge_after_s > 0:
        done, _pending = await asyncio.wait(tasks,
                                            timeout=st.hedge_after_s)
        if not done and budget[0] > 0:
            hedge_rep, _ = _choose(st, key, tried | {primary.id})
            if hedge_rep is not None:
                budget[0] -= 1
                hedged_id = hedge_rep.id
                st.obs.route_total.inc(reason="hedge")
                tasks.add(asyncio.create_task(_call_replica(
                    st, hedge_rep, name, raw, tried, headers)))
    winner = None
    pending = tasks
    while pending:
        done, pending = await asyncio.wait(
            pending, return_when=asyncio.FIRST_COMPLETED)
        for t in done:
            if not t.cancelled() and t.exception() is None:
                winner = t
                break
        if winner is not None:
            break
    for t in pending:
        t.cancel()
    if pending:
        await asyncio.gather(*pending, return_exceptions=True)
    if winner is None:
        return None
    status, payload, rep, trace = winner.result()
    hedge_won = hedged_id is not None and rep.id == hedged_id
    if hedge_won:
        st.obs.hedge_wins.inc()
    return status, payload, rep, hedge_won, trace


def _tenant_gate(st: _FleetState, request: web.Request):
    """Tenant admission at the fleet door. Returns (forward_headers,
    None) when admitted, or (None, 429 response) when the tenant's
    request bucket is empty. Always forwards X-Tenant so the replica's
    own ledger/scheduler sees the same identity the router billed."""
    headers = {"Content-Type": "application/json"}
    tenant_hdr = request.headers.get("X-Tenant", "")
    if tenant_hdr:
        headers["X-Tenant"] = tenant_hdr
    if st.ledger is not None:
        tname = st.tenancy.resolve(tenant_hdr).name
        try:
            st.ledger.check_request(tname)
        except Throttled as e:
            st.obs.tenant_throttled.inc(tenant=tname)
            return None, web.json_response(
                {"error": str(e)}, status=429,
                headers={"Retry-After": str(max(1, min(
                    60, math.ceil(e.retry_after))))})
        st.obs.tenant_requests.inc(tenant=tname)
    elif tenant_hdr:
        # tenant-blind router still counts per tenant, behind the
        # cardinality guard (the header is raw client input here)
        st.obs.tenant_requests.inc(
            tenant=st.obs.tenant_guard.admit(tenant_hdr))
    return headers, None


async def _routed_generate(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    name = request.match_info["name"]
    raw = await request.read()
    try:
        body = json.loads(raw)
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    fwd_headers, throttled = _tenant_gate(st, request)
    if throttled is not None:
        return throttled
    # Router-minted request id: forwarded to every dispatch (the
    # replica keys its token timeline and sequence checkpoints by it),
    # so a failover resume finds the dead replica's checkpoint and the
    # timeline survives the hop.
    rid = request.headers.get("X-Request-Id") or secrets.token_hex(8)
    fwd_headers["X-Request-Id"] = rid
    if isinstance(body, dict) and body.get("stream"):
        return await _routed_stream(request, st, name, raw, body,
                                    fwd_headers, rid)
    key = affinity_key(body, st.block_size)
    t0 = time.perf_counter()
    tried: set[str] = set()
    budget = [st.max_attempts]
    with st.obs.tracer.span("fleet.route", model=name) as span:
        for attempt in range(st.retries + 1):
            if budget[0] <= 0:
                break
            replica, reason = _choose(st, key, tried)
            if replica is None and tried:
                # every routable replica failed once this request:
                # transient faults (a chaos drop, a connection blip)
                # deserve a fresh sweep while attempt budget remains —
                # persistent corpses are held off by their circuit
                # breakers, not by this per-request memory
                tried.clear()
                replica, reason = _choose(st, key, tried)
            if replica is None:
                # fleet-wide blip: every replica dead or draining for a
                # beat (a lone survivor can trip its breaker to DEAD
                # with the heartbeat that would resurrect it still in
                # flight). Burn a retry waiting — the sleep yields the
                # event loop so that heartbeat can land — instead of
                # 503ing with attempt budget left.
                await asyncio.sleep(
                    min(st.backoff_s * (2 ** attempt), 1.0))
                continue
            if attempt:
                reason = "retry"
                await asyncio.sleep(
                    min(st.backoff_s * (2 ** (attempt - 1)), 1.0))
            # crash failover: a retry whose dead replica checkpointed
            # partial output resumes from it (re-prefill, decode only
            # the remainder) instead of regenerating from scratch
            dispatch_raw, prepend = raw, []
            ck = st.checkpoint_for(rid) if attempt else None
            if (ck is not None and ck.get("out")
                    and isinstance(body, dict)
                    and not body.get("logprobs")):
                rb, remaining = _resume_from_checkpoint(
                    body, ck, list(ck["out"]))
                if rb is not None and remaining > 0:
                    dispatch_raw, prepend = rb, list(ck["out"])
            result = await _race_hedged(st, replica, name,
                                        dispatch_raw, key, tried,
                                        name, fwd_headers, budget)
            if result is None:
                continue  # dispatched replicas failed; retry others
            status, payload, rep, hedge_won, trace = result
            if prepend and status == 200:
                payload = _splice_oneshot(
                    payload, prepend,
                    isinstance(body, dict) and "text" in body)
                st.obs.failover.inc()
            dt = time.perf_counter() - t0
            st.obs.route_total.inc(reason=reason)
            st.obs.route_latency.observe(dt, model=name, reason=reason)
            st.obs.slo.observe("fleet_route_latency", dt)
            st.obs.slo.record("fleet_availability", status < 500)
            span.attrs.update(replica=rep.id, reason=reason,
                              hedge_won=hedge_won, status=status)
            if trace:
                span.attrs["replica_trace"] = trace
            headers = {"X-Fleet-Replica": rep.id,
                       "X-Fleet-Route-Reason": reason,
                       "X-Request-Id": rid}
            if trace:
                headers["X-Fleet-Replica-Trace"] = trace
            return web.Response(body=payload, status=status,
                                content_type="application/json",
                                headers=headers)
        span.attrs["status"] = 503
    st.obs.slo.record("fleet_availability", False)
    return web.json_response(
        {"error": "no serving replica available"}, status=503,
        headers={"Retry-After": "1"})


async def _routed_stream(request: web.Request, st: _FleetState,
                         name: str, raw: bytes, body: dict,
                         fwd_headers: dict, rid: str):
    """SSE with mid-stream failover. The router PARSES the upstream
    event stream instead of blind passthrough: token events are
    re-emitted to the client as they arrive, and when the replica dies
    mid-stream (connection cut, 5xx, or a terminal error event) the
    router picks another replica, resumes from the heartbeat
    checkpoint — or re-issues the request and swallows the tokens the
    client already has — and splices the two halves into ONE stream
    with no duplicate or missing tokens. Retries before the first
    byte behave as before. No hedging: duplicating a stream would
    decode the prompt twice for one winner on every long request."""
    key = affinity_key(body, st.block_size)
    tried: set[str] = set()
    sent: list[int] = []   # token ids already forwarded to the client
    resp: web.StreamResponse | None = None
    text_mode = isinstance(body, dict) and "text" in body
    budget = st.max_attempts
    failed_over = False
    final_evt: dict | None = None
    for attempt in range(st.retries + 1):
        if budget <= 0 or final_evt is not None:
            break
        replica, reason = _choose(st, key, tried)
        if replica is None and tried:
            # same fresh sweep as the one-shot path: a transient fault
            # on the last untried replica must not strand the stream
            # while attempt budget remains
            tried.clear()
            replica, reason = _choose(st, key, tried)
        if replica is None:
            # same fleet-wide-blip wait as the one-shot path: hold the
            # stream open through a beat where nobody is routable
            # rather than abandoning it with budget left
            await asyncio.sleep(min(st.backoff_s * (2 ** attempt), 1.0))
            continue
        if attempt:
            reason = "retry"
            await asyncio.sleep(
                min(st.backoff_s * (2 ** (attempt - 1)), 1.0))
        dispatch_raw, skip = raw, 0
        if sent:
            # mid-stream failover: prefer the checkpoint (re-prefill
            # only), else splice onto the client's own token prompt,
            # else replay in full and swallow what was already sent
            ck = st.checkpoint_for(rid)
            if ck is not None and isinstance(ck.get("out"), list):
                rb, remaining = _resume_from_checkpoint(body, ck, sent)
                if remaining <= 0:
                    final_evt = {"done": True, "total": len(sent)}
                    break
                if rb is not None:
                    dispatch_raw = rb
            else:
                rb = _resume_from_body(body, sent)
                if rb is not None:
                    dispatch_raw = rb
                else:
                    dispatch_raw, skip = raw, len(sent)
            if not failed_over:
                failed_over = True
                st.obs.failover.inc()
        st.registry.note_dispatch(replica.id)
        budget -= 1
        try:
            await _chaos_gate(st, replica, name, dispatch_raw,
                              fwd_headers)
            async with st.session.post(
                    f"{replica.url}/v1/models/{name}:generate",
                    data=dispatch_raw,
                    headers=_inject_trace_context(st, fwd_headers),
                    timeout=aiohttp.ClientTimeout(
                        total=st.timeout_s)) as up:
                if up.status >= 500:
                    st.registry.note_failure(replica.id)
                    tried.add(replica.id)
                    continue
                if up.content_type != "text/event-stream":
                    payload = await up.read()
                    if resp is None:
                        # replica rejected pre-stream (4xx): passthrough
                        st.obs.route_total.inc(reason=reason)
                        return web.Response(
                            body=payload, status=up.status,
                            content_type="application/json",
                            headers={"X-Fleet-Replica": replica.id,
                                     "X-Request-Id": rid})
                    # resume rejected (e.g. peer started draining):
                    # retryable, the client stream is still open
                    tried.add(replica.id)
                    continue
                st.obs.route_total.inc(reason=reason)
                if resp is None:
                    headers = {
                        "Content-Type": "text/event-stream",
                        "Cache-Control": "no-cache",
                        "X-Fleet-Replica": replica.id,
                        "X-Request-Id": rid,
                    }
                    up_trace = up.headers.get("X-Trace-Id", "")
                    if up_trace:
                        headers["X-Fleet-Replica-Trace"] = up_trace
                    resp = web.StreamResponse(headers=headers)
                    await resp.prepare(request)
                buf = b""
                to_skip = skip
                upstream_error = False
                async for chunk in up.content.iter_any():
                    buf += chunk
                    while b"\n\n" in buf:
                        frame, buf = buf.split(b"\n\n", 1)
                        ev = _parse_sse_event(frame)
                        if ev is None:
                            continue
                        if "error" in ev:
                            # terminal error event: NOT forwarded —
                            # the router absorbs it and fails over
                            upstream_error = True
                            break
                        if ev.get("done"):
                            final_evt = ev
                            break
                        toks = ev.get("tokens")
                        if (not isinstance(toks, list) or not toks
                                or not isinstance(toks[0], list)
                                or not toks[0]):
                            continue
                        for tok in toks[0]:
                            if to_skip > 0:
                                to_skip -= 1
                                continue
                            sent.append(int(tok))
                            await resp.write(
                                b"data: " + json.dumps(
                                    {"tokens": [[int(tok)]]}).encode()
                                + b"\n\n")
                    if upstream_error or final_evt is not None:
                        break
                if upstream_error or final_evt is None:
                    # error event or connection ended with no terminal
                    # frame: the replica is gone mid-stream
                    st.registry.note_failure(replica.id)
                    tried.add(replica.id)
                    continue
                st.registry.note_success(replica.id)
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                _UpstreamError):
            # _UpstreamError covers a chaos-gate drop BEFORE the
            # dispatch: same failover path as a replica dying mid-frame
            st.registry.note_failure(replica.id)
            tried.add(replica.id)
        finally:
            st.registry.note_done(replica.id)
    if resp is None:
        return web.json_response(
            {"error": "no serving replica available"}, status=503,
            headers={"Retry-After": "1"})
    if final_evt is None:
        final = {"error": "no serving replica available",
                 "total": len(sent)}
    else:
        final = dict(final_evt)
        final["total"] = len(sent)
        if failed_over and final.get("done") and text_mode:
            # the resumed replica only saw the tail; rebuild the text
            # over the FULL spliced output (byte tokenizer mirror)
            final["text"] = _byte_decode_fleet(sent)
    await resp.write(b"data: " + json.dumps(final).encode() + b"\n\n")
    await resp.write_eof()
    return resp


# -- fleet control-plane endpoints ---------------------------------------


async def _register(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    url = body.get("url")
    if not isinstance(url, str) or not url.startswith("http"):
        return web.json_response(
            {"error": "body needs an http 'url'"}, status=400)
    models = body.get("models", [])
    if not isinstance(models, list):
        models = []
    rep = st.registry.register(
        url.rstrip("/"), replica_id=str(body.get("id", "")),
        models=[m for m in models if isinstance(m, str)],
        **{k: v for k, v in body.items()
           if k in ("queue_depth", "active_slots", "max_slots",
                    "kv_blocks_free", "kv_blocks_total")})
    st.ingest_checkpoints(rep.id, body.get("checkpoints"))
    log.info("fleet: registered replica %s at %s", rep.id, rep.url)
    return web.json_response({"id": rep.id, "state": rep.state})


async def _heartbeat(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    rid = str(body.get("id", ""))
    if st.chaos is not None and st.chaos.heartbeat_blackholed(rid):
        # chaos blackhole: swallow the beat (the replica believes it
        # landed; the sweeper sees staleness) — the crash-detection
        # path without killing anything
        return web.json_response({"ok": True})
    # sequence checkpoints ride the heartbeat raw payload (they are
    # NOT registry stats): fold them into the failover store first
    st.ingest_checkpoints(rid, body.get("checkpoints"))
    ok = st.registry.heartbeat(rid, **{
        k: v for k, v in body.items()
        if k in ("queue_depth", "active_slots", "max_slots",
                 "kv_blocks_free", "kv_blocks_total", "draining")})
    if not ok:
        # unknown id: the router restarted and lost its table — 404
        # tells the replica to re-register (server.py's beat loop does)
        return web.json_response(
            {"error": f"unknown replica {rid!r}"}, status=404)
    return web.json_response({"ok": True})


async def _deregister(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    rid = str(body.get("id", ""))
    removed = st.registry.deregister(rid)
    if removed:
        log.info("fleet: deregistered replica %s", rid)
    return web.json_response({"removed": removed})


async def _drain(request: web.Request):
    """Mark a replica draining in the table AND forward the drain to
    the replica itself — the scale-down path the ModelServer
    controller models. INSTANT drain: when healthy peers exist, the
    forwarded drain carries `{"migrate": true, "peers": [...]}` so the
    replica pushes every in-flight sequence (KV blocks included) to
    them and can exit in seconds instead of waiting out its longest
    generation. A lone replica falls back to the legacy wait-out
    drain — there is nowhere to migrate to."""
    st: _FleetState = request.app[FLEET_KEY]
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON"}, status=400)
    rid = str(body.get("id", ""))
    rep = st.registry.get(rid)
    if rep is None:
        return web.json_response(
            {"error": f"unknown replica {rid!r}"}, status=404)
    st.registry.drain(rid)
    peers = sorted(st.registry.routable({rid}),
                   key=lambda r: (r.load(), r.id))
    migrate = bool(peers) and body.get("migrate", True)
    payload = ({"migrate": True, "peers": [r.url for r in peers]}
               if migrate else None)
    forwarded: dict = {}
    try:
        async with st.session.post(
                f"{rep.url}/drain", json=payload,
                timeout=aiohttp.ClientTimeout(
                    total=30 if migrate else 5)) as r:
            if r.content_type == "application/json":
                forwarded = await r.json()
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
        pass  # marking it draining here already stops routing
    return web.json_response({"id": rid, "state": "draining",
                              "replica": forwarded})


async def _placements(request: web.Request):
    """GET /fleet/placements?exclude=a,b — advisory migration targets:
    healthy peers (least-loaded first) a draining replica should push
    its sequences to. `/fleet/drain` computes the same list itself;
    this endpoint serves operators and the chaos harness."""
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    excl = {e for e in
            request.rel_url.query.get("exclude", "").split(",") if e}
    peers = sorted(st.registry.routable(excl),
                   key=lambda r: (r.load(), r.id))
    return web.json_response({"peers": [r.url for r in peers],
                              "ids": [r.id for r in peers]})


async def _replicas(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    now = st.registry.clock()
    out = []
    for rep in st.registry.replicas():
        snap = rep.snapshot()
        snap["last_heartbeat_age_s"] = round(now - rep.last_heartbeat, 3)
        out.append(snap)
    return web.json_response({"replicas": out,
                              "counts": st.registry.counts()})


async def _autoscale(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    q = request.rel_url.query
    try:
        lo = int(q.get("min", 1))
        hi = int(q.get("max", 8))
        rec = autoscale.recommend_replicas(
            st.registry.replicas(), min_replicas=lo, max_replicas=hi)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    return web.json_response({"desired": rec.desired,
                              "reason": rec.reason,
                              "signals": rec.signals})


async def _stats(request: web.Request):
    """Machine-readable routing counters (the loadtest's evidence feed
    — same numbers as /metrics, without a Prometheus parse)."""
    st: _FleetState = request.app[FLEET_KEY]
    return web.json_response({
        "route_total": {reason: st.obs.route_total.value(reason=reason)
                        for reason in ROUTE_REASONS},
        "hedge_wins": st.obs.hedge_wins.value(),
        "failover": st.obs.failover.value(),
        "checkpoints": len(st.checkpoints),
        # fault-injection ledger (None outside chaos runs): the chaos
        # loadtest's proof that faults actually fired
        "chaos": dict(st.chaos.injected) if st.chaos else None,
    })


async def _scrape_replicas(st: _FleetState, path: str, *,
                           params: dict | None = None,
                           as_json: bool, timeout_s: float = 10.0):
    """GET `path` from every routable replica concurrently. Returns
    [(replica_id, body-or-None), ...] — None marks an unreachable or
    non-200 replica; the caller decides what a hole means."""
    st.registry.sweep()
    reps = sorted(st.registry.routable(set()), key=lambda r: r.id)

    async def fetch(rep):
        try:
            async with st.session.get(
                    f"{rep.url}{path}", params=params,
                    timeout=aiohttp.ClientTimeout(total=timeout_s)) as r:
                if r.status != 200:
                    return rep.id, None
                return rep.id, (await r.json() if as_json
                                else await r.text())
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                json.JSONDecodeError):
            return rep.id, None

    return await asyncio.gather(*(fetch(rep) for rep in reps))


async def _fleet_metrics(request: web.Request):
    """GET /fleet/metrics — one exposition for the whole fleet: every
    routable replica's /metrics scraped, strictly parsed, and merged
    (counters/gauges summed, histogram buckets merged on the union
    grid) with a `fleet_federation_up{replica}` coverage gauge. The
    router's OWN metrics stay at /metrics; federating them in would
    double-count once an external Prometheus scrapes both."""
    st: _FleetState = request.app[FLEET_KEY]
    scrapes = await _scrape_replicas(st, "/metrics", as_json=False)
    text = obs_lib.federate(dict(scrapes), guard=st.obs.replica_guard)
    return web.Response(text=text, content_type="text/plain")


async def _merged_traces(request: web.Request):
    """GET /debug/traces with cross-process merge: `?trace_id=` (the id
    from any X-Trace-Id header) additionally fetches each replica's
    segment of that trace and merges all Chrome events into one
    document, router and replicas as separate process tracks. Without
    `trace_id` (or with `format=summary`) this is the plain local
    endpoint every other app mounts."""
    st: _FleetState = request.app[FLEET_KEY]
    q = request.rel_url.query
    try:
        local = obs_lib.traces_response_payload(st.obs.tracer, q)
    except ValueError as e:
        raise web.HTTPBadRequest(text=str(e)) from None
    trace_id = q.get("trace_id") or None
    if trace_id is None or q.get("format") == "summary":
        return web.json_response(local)
    segments = [("router", local)]
    for rid, payload in await _scrape_replicas(
            st, "/debug/traces", params={"trace_id": trace_id},
            as_json=True):
        if isinstance(payload, dict) and payload.get("traceEvents"):
            segments.append((rid, payload))
    return web.json_response(obs_lib.merge_chrome_traces(segments))


async def _healthz(request: web.Request):
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    counts = st.registry.counts()
    return web.json_response({
        "status": "ok",
        "routable": counts["ready"] + counts["degraded"],
        "replicas": counts,
    })


async def _proxied_models(request: web.Request):
    """GET /v1/models via the least-loaded routable replica — clients
    written against a single server work unchanged through the door."""
    st: _FleetState = request.app[FLEET_KEY]
    st.registry.sweep()
    tried: set[str] = set()
    for _ in range(st.retries + 1):
        pool = st.registry.routable(tried)
        if not pool:
            break
        rep = min(pool, key=lambda r: (r.load(), r.id))
        try:
            async with st.session.get(
                    f"{rep.url}/v1/models",
                    timeout=aiohttp.ClientTimeout(total=10)) as r:
                payload = await r.read()
                if r.status >= 500:
                    raise _UpstreamError(str(r.status))
                return web.Response(
                    body=payload, status=r.status,
                    content_type="application/json",
                    headers={"X-Fleet-Replica": rep.id})
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                _UpstreamError):
            st.registry.note_failure(rep.id)
            tried.add(rep.id)
    return web.json_response(
        {"error": "no serving replica available"}, status=503)


def create_router_app(registry: ReplicaRegistry | None = None, *,
                      block_size: int = 64, policy: str = "affinity",
                      hedge_after_s: float = 2.0, retries: int = 3,
                      backoff_s: float = 0.05,
                      request_timeout_s: float = 300.0,
                      metrics_registry=None, tracer=None,
                      tenancy: TenancyConfig | None = None,
                      max_attempts: int | None = None,
                      chaos=None) -> web.Application:
    """Build the router app. `block_size` must match the replicas'
    `kv_block_size` (the affinity key is the first block — a mismatch
    only costs cache hits, never correctness). `policy` is "affinity"
    or "roundrobin" (the A/B control arm). `hedge_after_s <= 0`
    disables hedging. `metrics_registry`/`tracer` share external obs
    instances; by default the app owns fresh ones at `/metrics` and
    `/debug/traces`. `tenancy` enables router-side tenant rate
    limiting (`tenancy.TenancyConfig`, normally the same file the
    replicas load): a tenant over its requests/s bucket is 429'd at
    the fleet door before any replica dispatch. With or without it,
    the X-Tenant header is forwarded to replicas verbatim.
    `max_attempts` caps TOTAL upstream dispatches per request —
    primaries, retries and hedges together (default `retries + 2`).
    `chaos` is a `fleet.chaos.ChaosInjector` for the fault-injection
    loadtest; leave None in production."""
    if policy not in ("affinity", "roundrobin"):
        raise ValueError(f"unknown policy {policy!r}")
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    reg = registry if registry is not None else ReplicaRegistry()
    obs = FleetObs(reg, registry=metrics_registry, tracer=tracer)
    if tenancy is not None:
        # zero-seed the per-tenant series for every configured name
        for _t in tenancy.names():
            obs.tenant_guard.admit(_t)
            obs.tenant_requests.inc(0, tenant=_t)
            obs.tenant_throttled.inc(0, tenant=_t)
    st = _FleetState(reg, obs, block_size=block_size, policy=policy,
                     hedge_after_s=hedge_after_s, retries=retries,
                     backoff_s=backoff_s, timeout_s=request_timeout_s,
                     tenancy=tenancy, max_attempts=max_attempts,
                     chaos=chaos)
    app = web.Application(middlewares=[_router_obs_middleware])
    app[FLEET_KEY] = st

    async def _start(app_):
        st.session = aiohttp.ClientSession()

    async def _stop(app_):
        if st.session is not None:
            await st.session.close()

    app.on_startup.append(_start)
    app.on_cleanup.append(_stop)

    app.router.add_get("/healthz", _healthz)
    # /metrics via the shared helper; /debug/traces is the router's own
    # handler because it grows the cross-process ?trace_id= merge.
    app.router.add_get("/metrics",
                       obs_endpoints.metrics_handler(obs.registry))
    app.router.add_get("/debug/traces", _merged_traces)
    app.router.add_get("/fleet/metrics", _fleet_metrics)
    app.router.add_post("/fleet/register", _register)
    app.router.add_post("/fleet/heartbeat", _heartbeat)
    app.router.add_post("/fleet/deregister", _deregister)
    app.router.add_post("/fleet/drain", _drain)
    app.router.add_get("/fleet/placements", _placements)
    app.router.add_get("/fleet/replicas", _replicas)
    app.router.add_get("/fleet/autoscale", _autoscale)
    app.router.add_get("/fleet/stats", _stats)
    app.router.add_get("/v1/models", _proxied_models)
    app.router.add_post("/v1/models/{name}:generate", _routed_generate)
    return app
